"""Optimizer invariants, modeled on the reference's OptimizationVerifier
(analyzer/OptimizationVerifier.java:53-339): goal violations cleared or
reduced, hard goals never violated at the end, dead brokers evacuated,
proposals well-formed, model invariants (sanity_check) preserved.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer import annealer as AN
from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.analyzer import optimizer as OPT
from cruise_control_tpu.analyzer import proposals as PR
from cruise_control_tpu.models import fixtures
from cruise_control_tpu.models.cluster import Assignment
from cruise_control_tpu.ops.aggregates import device_topology
from cruise_control_tpu.ops.stats import sanity_check


def _hard_violations_after(result):
    return {s.name: s.violations_after for s in result.goal_summaries if s.hard}


def _check_invariants(topo, assign, result):
    # model invariants hold on the final assignment
    dt = device_topology(topo)
    checks = sanity_check(dt, result.final_assignment, topo.num_topics)
    assert all(checks.values()), checks
    # replicas of a partition sit on distinct brokers
    fb = np.asarray(result.final_assignment.broker_of)
    for p in range(topo.num_partitions):
        slots = topo.replicas_of_partition[p]
        slots = slots[slots >= 0]
        brokers = fb[slots]
        assert len(set(brokers.tolist())) == len(brokers), f"dup brokers p={p}"
    # no replica on a dead broker
    assert topo.broker_alive[fb].all()


@pytest.mark.smoke
def test_greedy_unbalanced():
    topo, assign = fixtures.unbalanced()
    r = OPT.optimize(topo, assign)
    assert r.engine == "greedy"
    assert r.num_replica_movements >= 1
    assert r.balancedness_after > r.balancedness_before
    _check_invariants(topo, assign, r)


def test_greedy_fixes_rack_awareness():
    topo, assign = fixtures.rack_aware_satisfiable()
    r = OPT.optimize(topo, assign)
    assert _hard_violations_after(r)["RackAwareGoal"] == 0
    _check_invariants(topo, assign, r)


def test_greedy_heals_dead_broker():
    topo, assign = fixtures.dead_broker()
    r = OPT.optimize(topo, assign)
    hv = _hard_violations_after(r)
    assert hv[G.SELF_HEALING_TERM] == 0
    assert all(v == 0 for v in hv.values()), hv
    _check_invariants(topo, assign, r)
    # the two replicas formerly on broker 0 moved somewhere alive
    moved = np.asarray(r.final_assignment.broker_of)[topo.replica_offline]
    assert (moved != 0).all()


def test_greedy_no_hard_regression_on_small():
    topo, assign = fixtures.small_cluster_model()
    r = OPT.optimize(topo, assign)
    hv = _hard_violations_after(r)
    assert all(v == 0 for v in hv.values()), hv
    _check_invariants(topo, assign, r)


@pytest.mark.smoke
def test_proposals_format():
    topo, assign = fixtures.small_cluster_model()
    # hand-move one replica: T1-0 follower from broker 2 to broker 1
    fb = np.asarray(assign.broker_of).copy()
    p0 = 0
    slots = topo.replicas_of_partition[p0]
    follower = [s for s in slots if s >= 0
                and s != int(np.asarray(assign.leader_of)[p0])][0]
    old_b = fb[follower]
    fb[follower] = 1 if old_b != 1 else 2
    final = Assignment(jnp.asarray(fb), assign.leader_of)
    props = PR.diff(topo, assign, final)
    assert len(props) == 1
    pr = props[0]
    assert pr.topic == "T1" and pr.partition == 0
    assert pr.old_leader == pr.old_replicas[0]
    assert set(pr.replicas_to_add) == {int(fb[follower])}
    assert set(pr.replicas_to_remove) == {int(old_b)}
    j = pr.to_json()
    assert j["topicPartition"] == {"topic": "T1", "partition": 0}


def test_proposals_leadership_only():
    topo, assign = fixtures.unbalanced3()
    first = topo.replicas_of_partition[:, 0]
    final = Assignment(assign.broker_of, jnp.asarray(first))
    props = PR.diff(topo, assign, final)
    assert len(props) == 2
    for p in props:
        assert p.has_leader_action and not p.has_replica_action


def test_balancedness_costs_sum_to_100():
    costs = OPT.balancedness_cost_by_goal(G.DEFAULT_GOALS)
    assert sum(costs.values()) == pytest.approx(100.0)
    # hard goals cost more than equal-priority soft goals would
    assert costs["RackAwareGoal"] > costs["ReplicaDistributionGoal"]


def test_annealer_small_random():
    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=9, num_replicas=300, num_topics=12), seed=7)
    r = OPT.optimize(topo, assign, engine="anneal",
                     anneal_config=AN.AnnealConfig(num_chains=8, steps=1024,
                                                   swap_interval=64))
    hv = _hard_violations_after(r)
    assert all(v == 0 for v in hv.values()), hv
    assert r.balancedness_after >= r.balancedness_before
    _check_invariants(topo, assign, r)


def test_annealer_heals_dead_brokers():
    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=9, num_replicas=200, num_topics=8,
        num_dead_brokers=1), seed=11)
    r = OPT.optimize(topo, assign, engine="anneal",
                     anneal_config=AN.AnnealConfig(num_chains=8, steps=1024,
                                                   swap_interval=64))
    assert _hard_violations_after(r)[G.SELF_HEALING_TERM] == 0
    _check_invariants(topo, assign, r)


# ---------------------------------------------------------------------------
# Lexicographic goal-priority property (OptimizationVerifier.java:53,112,339):
# optimizing the full goal list must not leave a higher-priority goal worse
# than optimizing its prefix alone achieves — the array-weighted objective
# must preserve the reference's sequential-priority semantics.
# ---------------------------------------------------------------------------

_LEX_PROPS = None


def _lex_fixture(seed):
    global _LEX_PROPS
    if _LEX_PROPS is None:
        _LEX_PROPS = fixtures.ClusterProperties(
            num_racks=3, num_brokers=8, num_replicas=240, num_topics=20,
            min_replication=3, max_replication=3)
    return fixtures.random_cluster(_LEX_PROPS, seed=1000 + seed)


def _viol_after(result):
    return {s.name: s.violations_after for s in result.goal_summaries}


#: prefix lengths checked: end of the hard block, then each early soft goal,
#: the usage-distribution block, and the full list
_PREFIX_POINTS = (6, 7, 8, 10, 13, 15)


@pytest.mark.parametrize("seed", range(20))
def test_lexicographic_goal_priority(seed):
    goals = list(G.DEFAULT_GOALS)
    topo, assign = _lex_fixture(seed)
    full = OPT.optimize(topo, assign, engine="greedy")
    vf = _viol_after(full)
    # hard goals always satisfied on these feasible fixtures
    for s in full.goal_summaries:
        if s.hard:
            assert s.violations_after == 0, (s.name, s.violations_after)
    for k in _PREFIX_POINTS[:-1]:
        prefix = tuple(goals[:k])
        pre = OPT.optimize(topo, assign, goal_names=prefix, engine="greedy")
        vp = _viol_after(pre)
        g = goals[k - 1]   # the lowest-priority goal of this prefix
        assert vf[g] <= vp[g] + 1e-6, (
            f"goal {g}: full-list optimization leaves {vf[g]} violations "
            f"but prefix-only achieves {vp[g]}")


def test_repair_row_kernel_matches_scalar_deltas():
    """repair._move_deltas_rows (broadcast [N, B] kernel) must agree exactly
    with the annealer's per-pair _move_delta on every (source, dest) —
    locks the two delta implementations together."""
    import jax.numpy as jnp2
    from cruise_control_tpu.analyzer import annealer as AN2
    from cruise_control_tpu.analyzer import objective as OBJ2
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.ops.aggregates import device_topology as devtopo
    import jax as jax2

    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=8, num_replicas=200, num_topics=15,
        min_replication=2, max_replication=3), seed=21)
    dt = devtopo(topo)
    th = G.compute_thresholds(
        dt, BalancingConstraint(),
        __import__("cruise_control_tpu.ops.aggregates", fromlist=["compute_aggregates"]
                   ).compute_aggregates(dt, assign, topo.num_topics))
    w = __import__("cruise_control_tpu.analyzer.objective",
                   fromlist=["build_weights"]).build_weights(G.DEFAULT_GOALS)
    opts = G.default_options(topo)
    init = jnp2.asarray(assign.broker_of)
    st = REP._chain_state(dt, assign, topo.num_topics, True)
    src = jnp2.asarray(np.arange(0, 200, 7), jnp2.int32)
    rows = REP._move_deltas_rows(dt, th, w, opts, st, init, src, True)

    def one(r, b):
        d2 = AN2._move_delta(dt, th, w, opts, st, init, "dense",
                             jnp2.full((1, 1), -1, jnp2.int32), r, b)
        return OBJ2.combine(d2)
    ref = jax2.vmap(jax2.vmap(one, in_axes=(None, 0)),
                    in_axes=(0, None))(src, jnp2.arange(dt.num_brokers))
    rows_np, ref_np = np.asarray(rows), np.asarray(ref)
    # illegal moves use different huge markers (raw _INF vs combined inf);
    # legality itself must agree exactly, legal deltas must agree numerically
    illegal_rows = rows_np >= 1e30
    illegal_ref = ref_np >= 1e30
    np.testing.assert_array_equal(illegal_rows, illegal_ref)
    legal = ~illegal_rows
    np.testing.assert_allclose(rows_np[legal], ref_np[legal],
                               rtol=1e-5, atol=1e-2)


def test_repair_does_not_consume_input_assignment():
    """repair() jits donate the chain state internally; the input Assignment
    must survive — calling repair twice on the same input (or reading the
    input afterwards) previously crashed with a deleted-buffer error."""
    import jax.numpy as jnp2
    from cruise_control_tpu.analyzer import objective as OBJ2
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.ops.aggregates import (
        compute_aggregates as agg2, device_topology as devtopo)

    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=8, num_replicas=200, num_topics=15,
        min_replication=2, max_replication=3), seed=5)
    dt = devtopo(topo)
    th = G.compute_thresholds(dt, BalancingConstraint(),
                              agg2(dt, assign, topo.num_topics))
    w = OBJ2.build_weights(G.DEFAULT_GOALS)
    opts = G.default_options(topo)
    init = jnp2.asarray(assign.broker_of)
    f1, m1, l1 = REP.repair(dt, assign, th, w, opts, topo.num_topics,
                            initial_broker_of=init, seed=0)
    # the input is intact and reusable
    np.asarray(assign.broker_of)
    f2, m2, l2 = REP.repair(dt, assign, th, w, opts, topo.num_topics,
                            initial_broker_of=init, seed=0)
    np.testing.assert_array_equal(np.asarray(f1.broker_of),
                                  np.asarray(f2.broker_of))
    assert (m1, l1) == (m2, l2)


# ---------------------------------------------------------------------------
# Lexicographic priority on the FLAGSHIP engine: the viol ladder + targeted
# repair (anneal path), not just the staged greedy, must preserve the
# reference's sequential-priority semantics (AbstractGoal.java:211).
# ---------------------------------------------------------------------------

_ANNEAL_LEX_CFG = None


def _anneal_lex_cfg():
    global _ANNEAL_LEX_CFG
    if _ANNEAL_LEX_CFG is None:
        from cruise_control_tpu.analyzer.annealer import AnnealConfig
        _ANNEAL_LEX_CFG = AnnealConfig(num_chains=8, steps=512,
                                       swap_interval=64, tries_move=16,
                                       tries_lead=4, tries_swap=8)
    return _ANNEAL_LEX_CFG


@pytest.mark.parametrize("seed", range(10))
def test_lexicographic_goal_priority_anneal_engine(seed):
    """20-seed greedy-engine property, run on the anneal+repair path at
    small scale: full-list optimization must not leave the lowest-priority
    goal of a prefix worse than prefix-only optimization achieves."""
    goals = list(G.DEFAULT_GOALS)
    topo, assign = _lex_fixture(seed)
    cfg = _anneal_lex_cfg()
    full = OPT.optimize(topo, assign, engine="anneal", anneal_config=cfg,
                        seed=seed)
    vf = _viol_after(full)
    for s in full.goal_summaries:
        if s.hard:
            assert s.violations_after == 0, (s.name, s.violations_after)
    for k in (6, 13):        # end of hard block; usage-distribution block
        prefix = tuple(goals[:k])
        pre = OPT.optimize(topo, assign, goal_names=prefix, engine="anneal",
                           anneal_config=cfg, seed=seed)
        vp = _viol_after(pre)
        g = goals[k - 1]
        assert vf[g] <= vp[g] + 1e-6, (
            f"goal {g}: full-list anneal leaves {vf[g]} violations "
            f"but prefix-only achieves {vp[g]}")


def test_repair_never_trades_up_the_violation_ladder():
    """The fused repair's batched multi-accept rounds (scatter-min claims)
    must never increase the weighted violation channel: the viol ladder
    makes one higher-tier violation outweigh every lower tier combined, so
    a net-improving accept set cannot trade a higher tier away."""
    import jax.numpy as jnp2
    from cruise_control_tpu.analyzer import objective as OBJ2
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.ops.aggregates import (
        compute_aggregates as agg2, device_topology as devtopo)

    for seed in range(5):
        topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
            num_racks=3, num_brokers=10, num_replicas=300, num_topics=20,
            min_replication=2, max_replication=3), seed=300 + seed)
        dt = devtopo(topo)
        th = G.compute_thresholds(dt, BalancingConstraint(),
                                  agg2(dt, assign, topo.num_topics))
        w = OBJ2.build_weights(G.DEFAULT_GOALS)
        opts = G.default_options(topo)
        init = jnp2.asarray(assign.broker_of)
        before = OBJ2.evaluate_objective(dt, assign, th, w, G.DEFAULT_GOALS,
                                         topo.num_topics, init)
        final, moves, leads = REP.repair(dt, assign, th, w, opts,
                                         topo.num_topics,
                                         initial_broker_of=init, seed=seed)
        after = OBJ2.evaluate_objective(dt, final, th, w, G.DEFAULT_GOALS,
                                        topo.num_topics, init)
        vb = float(np.asarray(before.value)[0])
        va = float(np.asarray(after.value)[0])
        assert va <= vb + 1e-3, (seed, vb, va)


def test_repair_host_claims_prevent_band_edge_double_count():
    """Band-edge regression for the host-claim dimension: with two brokers
    per host and host NW-in capacity just above current usage, two same-round
    winners moving onto sibling brokers of one host would double-count the
    shared host term's delta and could overshoot the host band. Host claims
    make same-host winners mutually exclusive per round; repair must end
    with zero host-capacity violations and no oscillation."""
    import jax.numpy as jnp2
    from cruise_control_tpu.analyzer import objective as OBJ2
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.ops.aggregates import (
        compute_aggregates as agg2, device_topology as devtopo)

    # 12 brokers on 6 hosts (2 each); skewed load so repair must move work
    # toward the emptier hosts without blowing their shared capacity
    import dataclasses as _dc
    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=12, num_replicas=360, num_topics=24,
        min_replication=2, max_replication=3), seed=77)
    topo = _dc.replace(
        topo, host_of_broker=(np.arange(12, dtype=np.int32) // 2))
    dt = devtopo(topo)
    th = G.compute_thresholds(dt, BalancingConstraint(),
                              agg2(dt, assign, topo.num_topics))
    w = OBJ2.build_weights(G.DEFAULT_GOALS)
    opts = G.default_options(topo)
    init = jnp2.asarray(assign.broker_of)
    final, moves, leads = REP.repair(dt, assign, th, w, opts, topo.num_topics,
                                     initial_broker_of=init, seed=0)
    after = OBJ2.evaluate_objective(dt, final, th, w, G.DEFAULT_GOALS,
                                    topo.num_topics, init)
    before = OBJ2.evaluate_objective(dt, assign, th, w, G.DEFAULT_GOALS,
                                     topo.num_topics, init)
    assert (float(np.asarray(after.value)[0])
            <= float(np.asarray(before.value)[0]) + 1e-3)


def test_diff_with_stats_matches_per_proposal_properties():
    """diff(with_stats=True)'s vectorized movement stats must equal the
    sums of the per-proposal property accessors (replicas_to_add,
    has_leader_action, inter_broker_data_to_move)."""
    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=10, num_replicas=400, num_topics=25,
        min_replication=2, max_replication=3), seed=99)
    r = OPT.optimize(topo, assign, engine="greedy")
    final = r.final_assignment
    props, n_moves, n_lead, data = PR.diff(topo, assign, final,
                                           with_stats=True)
    assert n_moves == sum(len(p.replicas_to_add) for p in props)
    assert n_lead == sum(1 for p in props if p.has_leader_action)
    assert data == pytest.approx(sum(p.inter_broker_data_to_move()
                                     for p in props), rel=1e-6)
    assert r.num_replica_movements == n_moves
    assert r.num_leadership_movements == n_lead


def test_hard_violation_backstop_engages_beyond_greedy_limit(monkeypatch):
    """A bad seed must not ship hard violations at scale: with the greedy
    polish unavailable (GREEDY_LIMIT forced to 0) and the MAIN repair pass
    crippled (max_rounds=0, i.e. a repair that converged short), the
    hard-only repair backstop must engage and clear the remaining hard
    violations (VERDICT r3 #10)."""
    from cruise_control_tpu.analyzer import repair as REP
    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=9, num_replicas=200, num_topics=8,
        num_dead_brokers=1), seed=11)
    monkeypatch.setattr(OPT, "GREEDY_LIMIT", 0)
    calls = []
    real_repair = REP.repair

    def counting_repair(*a, **kw):
        calls.append(kw.get("config"))
        return real_repair(*a, **kw)

    monkeypatch.setattr(REP, "repair", counting_repair)
    crippled = REP.RepairConfig(max_rounds=0)
    r = OPT.optimize(topo, assign, engine="anneal",
                     anneal_config=AN.AnnealConfig(num_chains=2, steps=8,
                                                   swap_interval=8),
                     seed=0, repair_config=crippled)
    # the main pass (and any polish cycles, which share its config) ran
    # crippled, then the backstop engaged with its own (full) defaults at
    # least once — backstop calls are the config=None ones
    assert calls[0] is crippled
    assert len(calls) >= 2
    assert any(c is None for c in calls[1:])
    hv = _hard_violations_after(r)
    assert all(v == 0 for v in hv.values()), hv


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_hard_zero_is_seed_property(seed):
    """The 0-hard-violations contract must hold at EVERY seed, not a lucky
    one (tools/seed_sweep.py pins the same property at LinkedIn scale on
    the TPU; this is the in-suite small-scale anchor)."""
    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=4, num_brokers=12, num_replicas=400, num_topics=10),
        seed=100 + seed)
    r = OPT.optimize(topo, assign, engine="anneal",
                     anneal_config=AN.AnnealConfig(num_chains=8, steps=256,
                                                   swap_interval=64),
                     seed=seed)
    hv = _hard_violations_after(r)
    assert all(v == 0 for v in hv.values()), (seed, hv)


@pytest.mark.parametrize("seed", [3, 11])
def test_lead_uphill_never_regresses(seed):
    """The lead phase's one-step-uphill escapes must never end worse than
    the plain descent: excursions commit only when their cumulative exact
    delta is negative and unwind otherwise."""
    import jax.numpy as jnp
    from cruise_control_tpu.analyzer import objective as OBJ
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.ops.aggregates import compute_aggregates
    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=9, num_replicas=300, num_topics=12),
        seed=seed)
    dt = device_topology(topo)
    agg0 = compute_aggregates(dt, assign, topo.num_topics)
    th = G.compute_thresholds(dt, BalancingConstraint(), agg0)
    w = OBJ.build_weights(G.DEFAULT_GOALS)
    opts = G.default_options(topo)
    init = jnp.asarray(assign.broker_of, jnp.int32)

    def quality(a):
        # the WEIGHTED two-channel objective — the uphill excursion may
        # legitimately trade several low-priority violations for one
        # higher-priority fix (raw counts can rise while the objective
        # strictly improves, which is the point of the priority ladder)
        ev = OBJ.evaluate_objective(
            dt, a, th, w, G.DEFAULT_GOALS, topo.num_topics, init,
            compute_aggregates(dt, a, topo.num_topics))
        v = np.asarray(ev.value, np.float64)
        return (float(v[0]), float(v[1]))

    base_cfg = REP.RepairConfig(fused_inner=32, fused_sources=64,
                                swap_partners=4, lead_uphill_steps=0)
    up_cfg = REP.RepairConfig(fused_inner=32, fused_sources=64,
                              swap_partners=4, lead_uphill_steps=8)
    a0, _, _ = REP.repair(dt, assign, th, w, opts, topo.num_topics,
                          config=base_cfg, seed=seed)
    a1, _, _ = REP.repair(dt, assign, th, w, opts, topo.num_topics,
                          config=up_cfg, seed=seed)
    assert quality(a1) <= quality(a0), (quality(a1), quality(a0))


def test_lead_swap_delta_matches_full_eval():
    """The compound leadership-pair kernel must agree with the full
    evaluator on the exact two-channel delta of applying BOTH handoffs
    (pairs share brokers, so singles' deltas are NOT additive — the union
    evaluation is the point). The state is preconditioned with a repair
    pass first: on a raw unoptimized state a broker carrying a 2^32-tier
    violation absorbs a +16-tier crossing inside broker_cost's f32 sum
    (the SAME precision model every delta kernel shares) — the kernels'
    operating regime is the post-descent state where high tiers are
    clear. Channels are compared separately in f64."""
    import jax
    import jax.numpy as jnp
    from cruise_control_tpu.analyzer import objective as OBJ
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.models.cluster import Assignment
    from cruise_control_tpu.ops.aggregates import compute_aggregates

    topo, assign = fixtures.synthetic_cluster(
        num_brokers=12, num_replicas=300, num_racks=3, num_topics=10,
        seed=5)
    dt = device_topology(topo)
    agg = compute_aggregates(dt, assign, topo.num_topics)
    th = G.compute_thresholds(dt, BalancingConstraint(), agg)
    w = OBJ.build_weights(G.DEFAULT_GOALS)
    opts = G.default_options(topo)
    init = jnp.asarray(assign.broker_of, jnp.int32)
    assign, _, _ = REP.repair(dt, assign, th, w, opts, topo.num_topics,
                              initial_broker_of=init, seed=5)
    st = REP._chain_state(dt, assign, topo.num_topics, True)
    reps = np.asarray(jax.device_get(dt.replicas_of_partition))
    lo = np.asarray(jax.device_get(st.leader_of))
    # ONE compiled program for all pairs: calling the kernel eagerly
    # compiles hundreds of tiny programs and pushes the suite over the
    # XLA CPU backend's cumulative-JIT segfault threshold (conftest)
    swap_delta = jax.jit(lambda p, sp, q, sq: REP._lead_swap_delta(
        dt, th, w, opts, st, p, sp, q, sq))

    def channels(leader_of):
        a2 = Assignment(broker_of=np.asarray(assign.broker_of),
                        leader_of=np.asarray(leader_of))
        ev = OBJ.evaluate_objective(dt, a2, th, w, G.DEFAULT_GOALS,
                                    topo.num_topics, init)
        v = np.asarray(jax.device_get(ev.penalties.violations), np.float64)
        c = np.asarray(jax.device_get(ev.penalties.cost), np.float64)
        wv = np.asarray(jax.device_get(w.per_goal_viol), np.float64)
        wc = np.asarray(jax.device_get(w.per_goal), np.float64)
        return float((v * wv).sum()), float((c * wc).sum())

    rng = np.random.default_rng(0)
    P, m = reps.shape
    checked = 0
    for _ in range(500):
        p, q = rng.integers(0, P, 2)
        sp, sq = rng.integers(0, m, 2)
        n1, n2 = reps[p, sp], reps[q, sq]
        if p == q or n1 < 0 or n2 < 0 or n1 == lo[p] or n2 == lo[q]:
            continue
        d = float(jax.device_get(swap_delta(
            jnp.int32(p), jnp.int32(sp), jnp.int32(q), jnp.int32(sq))))
        if d >= REP._INF * 0.5:
            continue
        lo2 = lo.copy()
        lo2[p] = n1
        lo2[q] = n2
        v0, c0 = channels(lo)
        v1, c1 = channels(lo2)
        exact = (v1 - v0) * OBJ.VIOL_SCALE + (c1 - c0)
        assert np.isclose(d, exact, rtol=1e-3, atol=5e-2), (
            p, sp, q, sq, d, exact)
        checked += 1
        if checked >= 40:
            break
    assert checked >= 20  # enough legal pairs actually compared
    jax.clear_caches()    # bound cumulative JIT code (see conftest)


def test_repair_clears_skewed_lbi_without_regression():
    """The measured stuck shape, small: one broker's leader-bytes-in far
    over its band (partitions it leads carry inflated LBI) while every
    other load axis stays balanced. The repair engine — singles, compound
    lead swaps, or the shed plan, whichever the state admits — must end
    with the LBI violation cleared and the exact weighted objective never
    worse than the input."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp
    from cruise_control_tpu.analyzer import objective as OBJ
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.ops.aggregates import compute_aggregates

    topo, assign = fixtures.synthetic_cluster(
        num_brokers=10, num_replicas=400, num_racks=5, num_topics=8,
        seed=11)
    lo = np.asarray(assign.leader_of)
    bo = np.asarray(assign.broker_of)
    lbi = np.asarray(topo.leader_bytes_in).copy()
    led_by_0 = bo[lo] == 0
    # x2: far enough over the band to violate, while each partition's lbi
    # stays well inside other brokers' band headroom (x6 made single
    # partitions bigger than ANY broker's headroom — unclearable by swaps)
    lbi[led_by_0] *= 2.0
    topo = dc.replace(topo, leader_bytes_in=lbi)
    dt = device_topology(topo)
    agg = compute_aggregates(dt, assign, topo.num_topics)
    th = G.compute_thresholds(dt, BalancingConstraint(), agg)
    w = OBJ.build_weights(G.DEFAULT_GOALS)
    opts = G.default_options(topo)
    init = jnp.asarray(assign.broker_of, jnp.int32)

    def quality(a):
        ev = OBJ.evaluate_objective(
            dt, a, th, w, G.DEFAULT_GOALS, topo.num_topics, init,
            compute_aggregates(dt, a, topo.num_topics))
        v = np.asarray(jax.device_get(ev.value), np.float64)
        return (float(v[0]), float(v[1]))

    def lbi_violations(a):
        bt_idx = G.BROKER_TERM_GOALS.index("LeaderBytesInDistributionGoal")
        agg2 = compute_aggregates(dt, a, 1)
        bt = G.broker_terms(th, agg2.broker_load,
                            agg2.replica_count.astype(np.float32),
                            agg2.leader_count.astype(np.float32),
                            agg2.potential_nw_out, agg2.leader_bytes_in)
        return float(np.asarray(
            jax.device_get(bt.violations))[:, bt_idx].sum())

    assert lbi_violations(assign) > 0     # the skew actually violates
    q0 = quality(assign)
    out, _, _ = REP.repair(dt, assign, th, w, opts, topo.num_topics,
                           seed=11)
    assert lbi_violations(out) == 0
    assert quality(out) <= q0
    jax.clear_caches()    # bound cumulative JIT code (see conftest)


def test_claim_subrounds_preserve_quality_contract():
    """The claim sub-rounds (round-4 third session) extend each fused
    round's matching over the SAME candidate matrices. Winners across all
    of a round's passes stay pairwise broker/partition/host-disjoint, so
    the captured deltas are exactly additive — descent quality must match
    the single-pass kernel's contract: never trade up the violation
    ladder, and end with the weighted violation channel no worse."""
    import jax
    import jax.numpy as jnp2
    from cruise_control_tpu.analyzer import objective as OBJ2
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.ops.aggregates import (
        compute_aggregates as agg2, device_topology as devtopo)

    for seed in (0, 3):
        topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
            num_racks=3, num_brokers=12, num_replicas=400, num_topics=20,
            min_replication=2, max_replication=3), seed=700 + seed)
        dt = devtopo(topo)
        th = G.compute_thresholds(dt, BalancingConstraint(),
                                  agg2(dt, assign, topo.num_topics))
        w = OBJ2.build_weights(G.DEFAULT_GOALS)
        opts = G.default_options(topo)
        init = jnp2.asarray(assign.broker_of)
        before = OBJ2.evaluate_objective(dt, assign, th, w, G.DEFAULT_GOALS,
                                         topo.num_topics, init)
        vb = float(np.asarray(before.value)[0])
        for min_brokers in (10 ** 9, 1):     # n_claim = 1 vs 4 sub-rounds
            cfg = REP.RepairConfig(claim_rounds_min_brokers=min_brokers)
            final, _, _ = REP.repair(dt, assign, th, w, opts,
                                     topo.num_topics, initial_broker_of=init,
                                     seed=seed, config=cfg)
            after = OBJ2.evaluate_objective(dt, final, th, w,
                                            G.DEFAULT_GOALS,
                                            topo.num_topics, init)
            va = float(np.asarray(after.value)[0])
            assert va <= vb + 1e-3, (seed, min_brokers, vb, va)
            dchecks = sanity_check(dt, final, topo.num_topics)
            assert all(dchecks.values()), (seed, min_brokers, dchecks)
    jax.clear_caches()    # bound cumulative JIT code (see conftest)


def test_topic_pair_candidates_respect_masks():
    """The device-side topic-escape candidate kernel must return sources
    of the requested topic on the requested broker (over mode) and
    partners of OTHER topics on brokers with band headroom only."""
    import jax
    import jax.numpy as jnp2
    from cruise_control_tpu.analyzer import objective as OBJ2
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.ops.aggregates import (
        compute_aggregates as agg2, device_topology as devtopo)

    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=9, num_replicas=300, num_topics=12,
        min_replication=2, max_replication=3), seed=42)
    dt = devtopo(topo)
    th = G.compute_thresholds(dt, BalancingConstraint(),
                              agg2(dt, assign, topo.num_topics))
    w = OBJ2.build_weights(G.DEFAULT_GOALS)
    st = REP._chain_state(dt, assign, topo.num_topics, True)
    en = REP._norm_load(dt.replica_base_load)
    movable = jnp2.ones((topo.num_replicas,), bool)
    t, b = 3, 2
    src, partners, valid = (np.asarray(x) for x in jax.device_get(
        REP._topic_pair_candidates(dt, th, st, movable, en,
                                   jnp2.int32(t), jnp2.int32(b),
                                   4, 8, "over")))
    bo = np.asarray(jax.device_get(st.broker_of))
    part_of = np.asarray(jax.device_get(dt.partition_of_replica))
    t_of_r = np.asarray(jax.device_get(dt.topic_of_partition))[part_of]
    cnt = np.zeros((topo.num_brokers, topo.num_topics), np.int64)
    np.add.at(cnt, (bo, t_of_r), 1)
    up = np.asarray(jax.device_get(th.topic_upper))
    si, ki = np.nonzero(valid)
    for i, k in zip(si.tolist(), ki.tolist()):
        r1, r2 = int(src[i]), int(partners[i, k])
        assert t_of_r[r1] == t and bo[r1] == b          # shed the cell
        assert t_of_r[r2] != t and bo[r2] != b          # other topic, off b
        assert cnt[bo[r2], t] < up[t]                   # t-headroom at dest
    jax.clear_caches()    # bound cumulative JIT code (see conftest)


def test_warm_escape_kernels_smoke_and_repair_after():
    """warm_escape_kernels must dispatch every escape kernel without
    touching the caller's assignment; a repair afterwards behaves
    normally (the warm states are throwaways)."""
    import jax
    import jax.numpy as jnp2
    from cruise_control_tpu.analyzer import objective as OBJ2
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.common.resources import BalancingConstraint
    from cruise_control_tpu.ops.aggregates import (
        compute_aggregates as agg2, device_topology as devtopo)

    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=10, num_replicas=300, num_topics=15,
        min_replication=2, max_replication=3), seed=77)
    dt = devtopo(topo)
    th = G.compute_thresholds(dt, BalancingConstraint(),
                              agg2(dt, assign, topo.num_topics))
    w = OBJ2.build_weights(G.DEFAULT_GOALS)
    opts = G.default_options(topo)
    bo_before = np.asarray(jax.device_get(assign.broker_of)).copy()
    REP.warm_escape_kernels(dt, assign, th, w, opts, topo.num_topics)
    assert (np.asarray(jax.device_get(assign.broker_of)) == bo_before).all()
    final, _, _ = REP.repair(dt, assign, th, w, opts, topo.num_topics,
                             seed=5)
    dchecks = sanity_check(dt, final, topo.num_topics)
    assert all(dchecks.values()), dchecks
    jax.clear_caches()    # bound cumulative JIT code (see conftest)


def test_basin_restart_skipped_in_healing_context(monkeypatch):
    """Self-healing / destination-constrained optimizations must never run
    the basin restart: the parked residual is structural there (the
    reference's ADD/REMOVE semantics ship such violations) and the full
    re-anneal from the original — broken — placement re-pays the whole
    pipeline for a candidate that cannot beat the constraint (measured:
    7.9 s discarded on the remove_broker bench)."""
    import dataclasses as dc

    from cruise_control_tpu.analyzer import annealer as AN

    topo, assign = fixtures.random_cluster(fixtures.ClusterProperties(
        num_racks=3, num_brokers=10, num_replicas=300, num_topics=15,
        min_replication=2, max_replication=3), seed=21)
    # dead broker + its replicas offline: the REMOVE self-healing topology
    alive = np.asarray(topo.broker_alive).copy()
    alive[0] = False
    bo = np.asarray(assign.broker_of)
    topo_rm = dc.replace(
        topo, broker_alive=alive,
        replica_offline=np.asarray(topo.replica_offline) | (bo == 0))
    opts_rm = G.build_options(topo_rm,
                              excluded_brokers_for_replica_move=(0,),
                              excluded_brokers_for_leadership=(0,))

    calls = []
    orig = AN.optimize_anneal

    def spy(*a, **kw):
        calls.append(kw.get("seed"))
        return orig(*a, **kw)

    monkeypatch.setattr(AN, "optimize_anneal", spy)
    cfg = AN.AnnealConfig(num_chains=8, steps=64, swap_interval=32)
    r = OPT.optimize(topo_rm, assign, options=opts_rm, engine="anneal",
                     anneal_config=cfg, seed=3)
    import jax
    # the basin restart's tell-tale seed offset (seed + 104729) must never
    # appear in a healing-context run, however many polish cycles ran
    assert (3 + 104729) not in calls, calls
    # the healing itself still happened: nothing remains on the dead broker
    final_bo = np.asarray(jax.device_get(r.final_assignment.broker_of))
    assert not (final_bo == 0).any()
    jax.clear_caches()    # bound cumulative JIT code (see conftest)
