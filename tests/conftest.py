"""Test environment: 8-device virtual CPU mesh.

Sharding/collective paths are exercised on a virtual 8-device CPU mesh; the
real TPU chip is reserved for bench runs (bench.py). The container's axon
sitecustomize registers a TPU-tunnel PJRT backend at interpreter startup whose
client creation dials a remote tunnel — unregister it here so CPU-only tests
never pay that cost.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax
    import jax._src.xla_bridge as _xb

    # jax was already imported at interpreter startup (sitecustomize), so the
    # env var alone is too late — update the live config too.
    jax.config.update("jax_platforms", "cpu")
    for _plat in ("axon", "tpu"):
        _xb._backend_factories.pop(_plat, None)
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
