"""Test environment: 8-device virtual CPU mesh.

Sharding/collective paths are exercised on a virtual 8-device CPU mesh; the
real TPU chip is reserved for bench runs (bench.py). The container's axon
sitecustomize registers a TPU-tunnel PJRT backend at interpreter startup whose
client creation dials a remote tunnel — unregister it here so CPU-only tests
never pay that cost.
"""

import os
import sys

# XLA's CPU backend JIT-compiles this repo's large fused programs with
# deeply recursive LLVM passes; on the default 8 MB main-thread stack a long
# suite intermittently segfaults inside backend_compile_and_load. The main
# stack grows on demand up to RLIMIT_STACK, so raising the soft limit here
# (before any compile) removes the crash without touching the system.
try:
    import resource
    _soft, _hard = resource.getrlimit(resource.RLIMIT_STACK)
    _want = 512 * 1024 * 1024
    if _soft != resource.RLIM_INFINITY and _soft < _want:
        _new = _want if _hard == resource.RLIM_INFINITY else min(_want, _hard)
        resource.setrlimit(resource.RLIMIT_STACK, (_new, _hard))
except Exception:
    pass

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax
    import jax._src.xla_bridge as _xb

    # jax was already imported at interpreter startup (sitecustomize), so the
    # env var alone is too late — update the live config too.
    jax.config.update("jax_platforms", "cpu")
    for _plat in ("axon", "tpu"):
        _xb._backend_factories.pop(_plat, None)
    # persistent compile cache for the CPU test backend (separate dir from
    # the TPU bench cache): the suite's wall-clock is dominated by XLA CPU
    # compiles of the large fused programs, and most tests recompile the
    # same (program, shape) pairs run after run — a warm cache turns a
    # >20-minute test_optimizer pass into mostly cache loads. Also applies
    # to the subprocess-spawning mesh tests.
    _cache_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache_cpu")
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # the config.update above only reaches THIS process; the subprocess-
    # isolated mesh tests (test_parallel.py) spawn clean interpreters that
    # read the env vars at jax import — export them so the subprocesses
    # share the same persistent cache instead of cold-compiling every run
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest


@pytest.fixture(autouse=True, scope="module")
def _release_compiled_programs():
    """Release compiled executables between test modules.

    The suite compiles a few hundred large fused programs; holding every
    executable alive for the whole run intermittently segfaults XLA's CPU
    backend inside ``backend_compile_and_load`` once cumulative JIT code
    crosses some internal limit (observed deterministically around test
    ~195: the NEXT fresh compile crashes, whichever program it is).
    Dropping the caches per module keeps live code bounded; modules rarely
    share shapes, so the recompile cost is negligible.
    """
    yield
    try:
        import jax
        jax.clear_caches()
    except Exception:
        pass


@pytest.fixture(autouse=True)
def _clear_chaos_hooks():
    """Process-global chaos hooks must never leak between tests: a test
    that installs an injection hook and fails before its cleanup would
    otherwise poison every later test touching the same site."""
    yield
    try:
        from cruise_control_tpu.common import faults
        faults.clear_chaos_hooks()
    except Exception:
        pass


_TESTS_SINCE_CLEAR = {"n": 0}


@pytest.fixture(autouse=True)
def _bound_cumulative_jit_within_module():
    """Also clear every 10 tests WITHIN a module: the round-4 repair engine
    (claim sub-rounds, topic-band escape kernels) grew per-test program
    count enough that test_optimizer alone crossed the cumulative-JIT crash
    threshold mid-module (segfault in ``backend_compile_and_load`` at test
    ~53). Ten tests keeps live code far below it while preserving most
    shared-shape executable reuse."""
    yield
    _TESTS_SINCE_CLEAR["n"] += 1
    if _TESTS_SINCE_CLEAR["n"] % 10 == 0:
        try:
            import jax
            jax.clear_caches()
        except Exception:
            pass
