"""Decision-provenance suite (ISSUE 14): per-move goal attribution, the
tick flight recorder, and deterministic audit replay.

What this file pins:

- ATTRIBUTION EXACTNESS: the batched attribution kernel's per-move
  per-goal deltas equal the brute-force ``full_goal_penalties(final) -
  full_goal_penalties(final with that move reverted)`` under the frozen
  thresholds, on the dense AND the sparse topic-scoring path.
- BIT-PARITY: provenance ON does not perturb the optimizer by one bit
  (the attribution is a read-only evaluation after the engines finish),
  and OFF — the default — stamps nothing.
- REST: ``GET /explain`` serves per-goal deltas for every move of the
  cached proposal (with the partition filter), ``GET /flightrecorder``
  the canonical JSONL log, through the real servlet.
- DETERMINISM: same-seed scenario flight logs are byte-identical ACROSS
  PROCESSES (subprocess sha256 comparison — stronger than in-process
  rerun, it catches dict-order / id() / env leaks into the canonical
  serialization).
- REPLAY: tools/replay_tick.py reproduces a flight-recorded scenario
  tick byte-identically and a fixture tick digest-identically, and its
  verdict audit re-derives goal verdicts on the rescore pipeline.

The slow tier replays a LinkedIn-shape tick (2,600 brokers / 50K
replicas / 3K topics — the uneven-shard sparse-topic regime) and pins
move-coverage + zero uncovered retraces for the attribution at scale.
"""

import hashlib
import json
import subprocess
import sys
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.analyzer import objective as OBJ
from cruise_control_tpu.analyzer import optimizer as OPT
from cruise_control_tpu.analyzer.annealer import AnnealConfig
from cruise_control_tpu.models import fixtures
from cruise_control_tpu.models.cluster import Assignment
from cruise_control_tpu.obs import provenance as PV
from cruise_control_tpu.obs.flightrec import (FlightRecorder, canonical_record,
                                              load_jsonl)
from cruise_control_tpu.ops.aggregates import (compute_aggregates,
                                               device_topology, topic_totals)

pytestmark = pytest.mark.obs

ROOT = Path(__file__).resolve().parents[1]

#: matches test_obs/test_rawspeed so tier-1 reuses compiled programs
CFG = AnnealConfig(num_chains=8, steps=128, swap_interval=32,
                   tries_move=8, tries_lead=4, tries_swap=4)


def _optimize(topo, assign, **kw):
    kw.setdefault("engine", "anneal")
    kw.setdefault("anneal_config", CFG)
    kw.setdefault("seed", 5)
    kw.setdefault("polish_cycles", 0)
    return OPT.optimize(topo, assign, **kw)


def _revert_move(dt, final, base, p):
    """final with partition p's placement+leadership put back to base."""
    reps = np.asarray(dt.replicas_of_partition[p])
    valid = reps >= 0
    r = np.clip(reps, 0, None)
    bo = np.asarray(final.broker_of).copy()
    bo[r[valid]] = np.asarray(base.broker_of)[r[valid]]
    lo = np.asarray(final.leader_of).copy()
    lo[p] = np.asarray(base.leader_of)[p]
    return Assignment(broker_of=jnp.asarray(bo), leader_of=jnp.asarray(lo))


#: brute-force spot-check budget — the kernel's full coverage is pinned
#: separately (payload test); re-scoring every move host-side is O(moves)
#: full evaluations and would dominate tier-1 wall time
_BRUTE_MOVES = 8


def _brute_force_check(dt, final, base, th, init_broker, goal_names,
                       num_topics, sparse_topic, attr, atol=1e-4):
    def full(a):
        pen = G.full_goal_penalties(dt, a, th, num_topics, goal_names,
                                    initial_broker_of=init_broker,
                                    sparse_topic=sparse_topic)
        return np.asarray(pen.violations), np.asarray(pen.cost)

    v_fin, c_fin = full(final)
    # worst-impact head + tail: the sorted extremes catch sign/scale slips
    idx = list(range(len(attr.partitions)))
    idx = idx[:_BRUTE_MOVES - 1] + idx[-1:] if len(idx) > _BRUTE_MOVES \
        else idx
    for i in idx:
        p = attr.partitions[i]
        rev = _revert_move(dt, final, base, int(p))
        v_rev, c_rev = full(rev)
        np.testing.assert_allclose(attr.violations_delta[i], v_fin - v_rev,
                                   atol=atol, err_msg=f"partition {int(p)}")
        np.testing.assert_allclose(attr.cost_delta[i], c_fin - c_rev,
                                   atol=atol, err_msg=f"partition {int(p)}")


@pytest.mark.parametrize("fixture", ["unbalanced", "dead_broker"])
def test_attribution_matches_brute_force_dense(fixture):
    """Every per-move per-goal delta from the one batched kernel equals the
    revert-and-rescore brute force under the same frozen thresholds."""
    topo, assign = getattr(fixtures, fixture)()
    res = _optimize(topo, assign, provenance=True)
    goal_names = tuple(G.DEFAULT_GOALS)
    (constraint, opts, dt, num_topics, sparse_topic, init_broker, _agg,
     agg0, th, weights) = OPT._setup_model(topo, assign, goal_names, None,
                                           None, None)
    assert not sparse_topic
    final = res.final_assignment
    agg_after = compute_aggregates(dt, final, num_topics)
    attr = PV.attribute_proposal(dt, final, assign, th, agg_after,
                                 init_broker, goal_names, num_topics,
                                 sparse_topic)
    assert attr.num_moves > 0
    _brute_force_check(dt, final, base=assign, th=th,
                       init_broker=init_broker, goal_names=goal_names,
                       num_topics=num_topics, sparse_topic=False, attr=attr)


def test_attribution_matches_brute_force_sparse():
    """The sparse topic-scoring path (the LinkedIn-scale routing) at toy
    shapes: thresholds/aggregates computed in sparse mode, kernel vs brute
    force both sparse."""
    topo, assign = fixtures.synthetic_cluster(
        num_brokers=12, num_replicas=240, num_racks=3, num_topics=8, seed=3)
    res = _optimize(topo, assign, seed=3)
    goal_names = tuple(G.DEFAULT_GOALS)
    from cruise_control_tpu.common.resources import BalancingConstraint
    dt = device_topology(topo)
    num_topics = topo.num_topics
    tt = topic_totals(dt, num_topics)
    th = G.compute_thresholds(dt, BalancingConstraint(),
                              compute_aggregates(dt, assign, 1),
                              topic_total=tt)
    init_broker = jnp.asarray(np.asarray(assign.broker_of, np.int32))
    final = res.final_assignment
    agg_after = compute_aggregates(dt, final, 1)
    attr = PV.attribute_proposal(dt, final, assign, th, agg_after,
                                 init_broker, goal_names, num_topics,
                                 sparse_topic=True)
    assert attr.num_moves > 0
    _brute_force_check(dt, final, base=assign, th=th,
                       init_broker=init_broker, goal_names=goal_names,
                       num_topics=num_topics, sparse_topic=True, attr=attr)


@pytest.mark.parametrize("fixture", ["unbalanced", "small_cluster_model",
                                     "dead_broker"])
def test_provenance_on_off_bit_parity(fixture):
    """Attribution is a read-only evaluation after the engines finish:
    provenance ON must produce the same assignment bit for bit as OFF
    (the default), which stamps nothing."""
    topo, assign = getattr(fixtures, fixture)()
    plain = _optimize(topo, assign)
    explained = _optimize(topo, assign, provenance=True)
    a, b = plain.final_assignment, explained.final_assignment
    assert np.array_equal(np.asarray(a.broker_of), np.asarray(b.broker_of))
    assert np.array_equal(np.asarray(a.leader_of), np.asarray(b.leader_of))
    assert plain.violated_goals_after == explained.violated_goals_after
    assert [p.to_json() for p in plain.proposals] == \
           [p.to_json() for p in explained.proposals]
    assert plain.move_attribution is None
    assert "moveAttribution" not in plain.to_json()
    ma = explained.move_attribution
    assert ma is not None and ma["numMoves"] == len(ma["moves"])
    assert "moveAttribution" in explained.to_json()


def test_attribution_payload_covers_every_move_and_goal():
    """The /explain payload contract: one entry per changed partition
    (matching the decoded proposals), per-goal delta vectors over
    goals + the self-healing term, sorted worst-impact-first."""
    topo, assign = fixtures.unbalanced()
    res = _optimize(topo, assign, provenance=True)
    ma = res.move_attribution
    want_goals = list(G.DEFAULT_GOALS) + [G.SELF_HEALING_TERM]
    assert ma["goals"] == want_goals
    moved = {f"{p.topic}-{p.partition}" for p in res.proposals}
    attributed = {m["topicPartition"] for m in ma["moves"]}
    assert attributed == moved        # every move of the proposal explained
    scores = []
    for m in ma["moves"]:
        assert len(m["violationsDelta"]) == len(want_goals)
        assert len(m["costDelta"]) == len(want_goals)
        scores.append(OBJ.VIOL_SCALE * sum(m["violationsDelta"])
                      + sum(m["costDelta"]))
    assert scores == sorted(scores)   # most penalty-removing first


# ------------------------------------------------------ REST + flight log

from cruise_control_tpu.app import CruiseControlApp
from cruise_control_tpu.common.config import CruiseControlConfig
from cruise_control_tpu.executor.executor import FakeClusterAdapter
from cruise_control_tpu.monitor.load_monitor import StaticMetadataSource
from cruise_control_tpu.monitor.sampler import (
    BrokerMetadata,
    ClusterMetadata,
    PartitionMetadata,
    SyntheticLoadSampler,
)
from cruise_control_tpu.server import rest

W = 60_000


def _metadata(num_brokers=6, num_parts=30, rf=2):
    brokers = [BrokerMetadata(i, rack=f"r{i % 3}", host=f"h{i}")
               for i in range(num_brokers)]
    parts = []
    for p in range(num_parts):
        reps = tuple((p + j) % num_brokers for j in range(rf))
        parts.append(PartitionMetadata("T", p, leader=reps[0],
                                       replicas=reps))
    return ClusterMetadata(brokers=brokers, partitions=parts, generation=1)


def _prov_app():
    cfg = CruiseControlConfig({
        "optimizer.engine": "greedy",
        "partition.metrics.window.ms": W,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "execution.progress.check.interval.ms": 1,
        "failed.brokers.file.path": "",
        "obs.provenance.enable": True,
    })
    md = _metadata()
    adapter = FakeClusterAdapter(
        {f"{p.topic}-{p.partition}": tuple(p.replicas)
         for p in md.partitions}, latency_polls=1)
    app = CruiseControlApp(cfg, StaticMetadataSource(md),
                           SyntheticLoadSampler(seed=4),
                           cluster_adapter=adapter)
    app.load_monitor._now = lambda: 4 * W
    for w in range(4):
        app.load_monitor.sample_once(now_ms=w * W + 30_000)
    return app


@pytest.fixture(scope="module")
def prov_server():
    app = _prov_app()
    app.precompute_tick()
    srv = rest.serve(app, port=0)
    yield srv
    srv.shutdown()


def _get(srv, path):
    port = srv.server_address[1]
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get_raw(srv, path):
    port = srv.server_address[1]
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def test_rest_explain_serves_per_move_attribution(prov_server):
    code, body = _get(prov_server, "/kafkacruisecontrol/explain")
    assert code == 200
    assert body["provenanceEnabled"] is True
    assert body["isProposalReady"] is True
    ma = body["moveAttribution"]
    assert ma["numMoves"] >= 1
    for m in ma["moves"]:
        assert len(m["violationsDelta"]) == len(ma["goals"])
        assert len(m["costDelta"]) == len(ma["goals"])
    # partition filter narrows to one topic-partition
    tp = ma["moves"][0]["topicPartition"]
    code, one = _get(prov_server,
                     f"/kafkacruisecontrol/explain?partition={tp}")
    assert code == 200
    got = one["moveAttribution"]["moves"]
    assert got and all(m["topicPartition"] == tp for m in got)


def test_rest_flightrecorder_exports_canonical_jsonl(prov_server):
    code, ctype, text = _get_raw(prov_server,
                                 "/kafkacruisecontrol/flightrecorder")
    assert code == 200
    assert ctype.startswith("text/plain")
    recs = load_jsonl(text)
    ticks = [r for r in recs if r["kind"] == "tick"]
    assert ticks
    t = ticks[-1]
    for key in ("seq", "tsMs", "engine", "outcome", "proposalDigest",
                "violatedGoalsAfter", "numReplicaMovements"):
        assert key in t, key
    # provenance is on: the record keeps the top attributed moves
    assert t["numAttributedMoves"] >= 1
    assert t["topMoves"]
    # canonical bytes: every line round-trips through canonical_record
    for line in text.splitlines():
        assert canonical_record(json.loads(line)) == line
    # ?format=json wraps records + ring summary
    code, body = _get(prov_server,
                      "/kafkacruisecontrol/flightrecorder?format=json")
    assert code == 200
    assert body["summary"]["records"] == len(body["records"])


def test_state_carries_flight_recorder_summary(prov_server):
    code, body = _get(prov_server, "/kafkacruisecontrol/state")
    assert code == 200
    fr = body["ObservabilityState"]["flightRecorder"]
    assert fr["enabled"] is True
    assert fr["records"] >= 1


# -------------------------------------------- determinism + audit replay

def _flight_scenario():
    from cruise_control_tpu.simulator import Scenario
    return Scenario(name="prov-audit", seed=11, ticks=2, warmup_ticks=1)


@pytest.fixture(scope="module")
def scenario_card():
    from cruise_control_tpu.simulator import run_scenario
    return run_scenario(_flight_scenario())


def test_flight_log_byte_identical_across_processes(scenario_card):
    """Same-seed determinism held to the strongest standard: a fresh
    PROCESS exports the byte-identical flight log (sha256 compared), so
    no id()/hash-seed/dict-order artifact leaks into the canonical
    serialization."""
    assert scenario_card.flight_log
    want = hashlib.sha256(scenario_card.flight_log.encode()).hexdigest()
    body = f"""
import sys, hashlib
sys.path.insert(0, {str(ROOT)!r})
from cruise_control_tpu.simulator import Scenario, run_scenario
card = run_scenario(Scenario(name="prov-audit", seed=11, ticks=2,
                             warmup_ticks=1))
print("FLIGHTSHA", hashlib.sha256(card.flight_log.encode()).hexdigest())
"""
    import os
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", body], env=env,
                         capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-3000:]
    got = [ln.split()[1] for ln in out.stdout.splitlines()
           if ln.startswith("FLIGHTSHA")][0]
    assert got == want
    # and the scorecard core carries the same digest both sides diff on
    assert scenario_card.core["flightRecorder"]["records"] == \
        len(load_jsonl(scenario_card.flight_log))


def test_replay_tool_reproduces_scenario_tick(scenario_card):
    """tools/replay_tick.py scenario mode: rebuild the scenario from the
    record's embedded spec, re-run it, and the record at the same seq is
    byte-identical — digests included."""
    sys.path.insert(0, str(ROOT))
    from tools.replay_tick import replay_log
    verdict = replay_log(scenario_card.flight_log)
    assert verdict["mode"] == "scenario"
    assert verdict["reproduced"] is True


def test_replay_tool_fixture_mode_and_tamper_detection():
    """Fixture mode round-trips (digest pin, optimize re-run, independent
    rescore verdict audit) and a tampered proposalDigest is REFUSED —
    the failure the tool exists to catch must actually fail."""
    sys.path.insert(0, str(ROOT))
    from tools.replay_tick import ReplayError, record_fixture_tick, replay_log
    log = record_fixture_tick("unbalanced")
    verdict = replay_log(log)
    assert verdict["reproduced"] is True
    assert verdict["proposalDigest"] == load_jsonl(log)[0]["proposalDigest"]
    tampered = json.loads(log.splitlines()[0])
    tampered["proposalDigest"] = "0" * 64
    with pytest.raises(ReplayError, match="did NOT reproduce"):
        replay_log(canonical_record(tampered) + "\n")


def test_rescore_score_state_matches_optimizer_verdicts():
    """analyzer.rescore.score_state (the replay tool's audit primitive):
    frozen-threshold scoring of the final state reproduces the optimizer's
    own violated_goals_after (dead_broker exercises the self-healing
    term; the third fixture shape is covered by the replay tests)."""
    from cruise_control_tpu.analyzer import rescore as RS
    for fixture in ("unbalanced", "dead_broker"):
        topo, assign = getattr(fixtures, fixture)()
        res = _optimize(topo, assign)
        names_ext, violated, _pen = RS.score_state(
            topo, res.final_assignment, G.DEFAULT_GOALS, None,
            initial_assign=assign)
        audited = [g for g, v in zip(names_ext, violated) if v]
        assert audited == res.violated_goals_after, fixture


def test_flight_recorder_ring_bounds_and_seq_monotonic():
    clock = [0.0]
    rec = FlightRecorder(now_fn=lambda: clock[0], capacity=4)
    rec.set_context(source="test:ring")
    for i in range(10):
        clock[0] = float(i)
        rec.record("tick", {"i": i})
    recs = rec.records()
    assert len(recs) == 4
    assert [r["seq"] for r in recs] == [6, 7, 8, 9]   # never reused
    assert rec.summary()["dropped"] == 6
    disabled = FlightRecorder(now_fn=lambda: 0.0, enabled=False)
    disabled.record("tick", {})
    assert disabled.records() == []
    assert disabled.export_jsonl() == ""


# --------------------------------------------------------- slow at scale

@pytest.mark.slow
def test_linkedin_shape_explain_and_replay():
    """The acceptance shapes: 2,600 brokers / 50K replicas / 3K topics
    (sparse topic routing, uneven shard tail). The attribution covers
    every changed partition with ZERO uncovered retraces on the second
    (steady-state) run, and replay_tick reproduces the recorded tick
    digest-identically."""
    from cruise_control_tpu.common import sentinels as SENT

    fx_kwargs = dict(num_brokers=2_600, num_replicas=50_000, num_racks=40,
                     num_topics=3_000, seed=5)
    anneal = dict(num_chains=8, steps=16, swap_interval=8,
                  tries_move=48, tries_lead=8, tries_swap=24)
    topo, assign = fixtures.synthetic_cluster(**fx_kwargs)
    cfg = AnnealConfig(**anneal)
    kw = dict(engine="anneal", anneal_config=cfg, seed=5, provenance=True)
    OPT.optimize(topo, assign, **kw)            # compile pass
    with SENT.retrace_sentinel() as log:
        res = OPT.optimize(topo, assign, **kw)
    assert not SENT.check_steady_state(log), log.summary()
    ma = res.move_attribution
    moved = {f"{p.topic}-{p.partition}" for p in res.proposals}
    assert {m["topicPartition"] for m in ma["moves"]} == moved
    assert ma["numMoves"] == len(moved) > 0

    sys.path.insert(0, str(ROOT))
    from tools.replay_tick import record_fixture_tick, replay_log
    rec_log = record_fixture_tick("synthetic_cluster", seed=5,
                                  engine="anneal", fixture_kwargs=fx_kwargs,
                                  anneal=anneal)
    verdict = replay_log(rec_log)
    assert verdict["reproduced"] is True
