"""graftlint: per-rule trigger/clean fixtures, the whole-package gate, and
the runtime steady-state sentinels.

Every rule (G001-G009 and the concurrency family G101-G105) gets (a) a
fixture snippet that TRIGGERS it and (b) a
clean-idiom snippet that must pass — so a rule that silently stops firing
(or starts over-firing) breaks here, not in a downstream repo sweep.  The
gate test is the CI tentpole: the whole ``cruise_control_tpu`` package plus
``bench.py`` must lint clean against the checked-in baseline.
"""

import json
import textwrap

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tools.graftlint import engine as LE  # noqa: E402
from tools.graftlint.engine import apply_baseline, lint, lint_source, \
    load_baseline  # noqa: E402

pytestmark = pytest.mark.lint

#: a hot-path module location (G002/G005 scope off the pretended path)
HOT = "cruise_control_tpu/analyzer/annealer.py"


def _codes(src, path="cruise_control_tpu/models/somefile.py", select=None):
    return [f.code for f in lint_source(textwrap.dedent(src), path=path,
                                        select=select)]


# -- G001: traced-value Python control flow inside jit ---------------------

def test_g001_triggers_on_traced_if():
    src = """
    import jax, jax.numpy as jnp

    @jax.jit
    def f(x):
        if x > 0:
            return x
        return -x
    """
    assert "G001" in _codes(src)


def test_g001_triggers_on_partial_jit_while():
    src = """
    import jax, jax.numpy as jnp
    from functools import partial

    @partial(jax.jit, static_argnames=("n",))
    def f(x, n):
        while jnp.sum(x) > 0:
            x = x - 1
        return x
    """
    assert "G001" in _codes(src)


def test_g001_clean_on_static_and_shape_tests():
    src = """
    import jax, jax.numpy as jnp
    from functools import partial

    @partial(jax.jit, static_argnames=("mode",))
    def f(x, mode, y=None):
        if mode == "fast":        # static arg: fine
            x = x * 2
        if y is None:             # structural test: fine
            y = x
        if x.ndim == 2:           # shape metadata: fine
            x = x.sum(axis=0)
        return jnp.where(x > 0, x, -x)   # device branch: the clean idiom
    """
    assert "G001" not in _codes(src)


# -- G002: implicit host sync in hot loops ---------------------------------

def test_g002_triggers_on_item_in_hot_loop():
    src = """
    import jax.numpy as jnp

    def step(xs):
        total = 0.0
        for x in xs:
            total += x.item()
        return total
    """
    assert "G002" in _codes(src, path=HOT)


def test_g002_triggers_on_float_coercion_of_device_value():
    src = """
    import jax.numpy as jnp

    def step(xs):
        out = []
        for x in xs:
            out.append(float(jnp.sum(x)))
        return out
    """
    assert "G002" in _codes(src, path=HOT)


def test_g002_triggers_on_bare_asarray_in_hot_loop():
    src = """
    import numpy as np

    def step(batches):
        outs = []
        for b in batches:
            outs.append(np.asarray(b))
        return outs
    """
    assert "G002" in _codes(src, path=HOT)


def test_g002_clean_on_explicit_device_get():
    src = """
    import jax
    import numpy as np

    def step(batches):
        outs = []
        for b in batches:
            outs.append(np.asarray(jax.device_get(b)))
        return outs
    """
    assert "G002" not in _codes(src, path=HOT)


def test_g002_clean_on_host_list_and_outside_hot_modules():
    src = """
    import numpy as np

    def step(n):
        sim = list(range(n))
        out = []
        for i in range(3):
            out.append(np.asarray(sim, np.int64))
        return out
    """
    assert "G002" not in _codes(src, path=HOT)
    # same .item() code OUTSIDE the hot-module list: not G002's business
    cold = """
    def step(xs):
        return [x.item() for x in xs]
    """
    assert "G002" not in _codes(cold, path="cruise_control_tpu/app.py")


# -- G003: device allocation inside a Python loop --------------------------

def test_g003_triggers_on_alloc_in_loop():
    src = """
    import jax, jax.numpy as jnp

    def f(n):
        outs = []
        for i in range(n):
            outs.append(jnp.zeros((8,), jnp.float32))
            jax.device_put(i)
        return outs
    """
    codes = _codes(src)
    assert codes.count("G003") == 2


def test_g003_clean_on_hoisted_alloc_and_inline_disable():
    src = """
    import jax.numpy as jnp

    def f(n):
        z = jnp.zeros((8,), jnp.float32)     # hoisted: fine
        outs = []
        for i in range(n):
            outs.append(z + i)
            w = jnp.zeros((4,), jnp.int32)  # graftlint: disable=G003
        return outs
    """
    assert "G003" not in _codes(src)


def test_g003_not_confused_by_defs_inside_loops():
    # a def inside a loop DEFINES code per iteration; the allocation in its
    # body does not run per loop iteration
    src = """
    import jax.numpy as jnp

    def f(n):
        fns = []
        for i in range(n):
            def g():
                return jnp.zeros((4,), jnp.float32)
            fns.append(g)
        return fns
    """
    assert "G003" not in _codes(src)


# -- G004: non-static Python state captured by jit -------------------------

def test_g004_triggers_on_mutable_default_and_global_read():
    src = """
    import jax

    _CACHE = {}

    @jax.jit
    def f(x, opts=[]):
        return x + len(_CACHE)
    """
    codes = _codes(src)
    assert codes.count("G004") == 2


def test_g004_clean_on_passed_state():
    src = """
    import jax

    @jax.jit
    def f(x, scale):
        return x * scale
    """
    assert "G004" not in _codes(src)


# -- G005: dtype-promotion hazards -----------------------------------------

def test_g005_triggers_on_dtypeless_np_alloc_in_jit():
    src = """
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        return x + np.zeros(4)
    """
    assert "G005" in _codes(src)


def test_g005_triggers_on_literal_array_in_hot_module():
    src = """
    import numpy as np

    def f():
        return np.array([1, 2, 3])
    """
    assert "G005" in _codes(src, path=HOT)


def test_g005_clean_on_explicit_dtype_and_preserving_conversions():
    src = """
    import jax
    import numpy as np

    def f(x, host_arr):
        a = np.zeros(4, np.float32)              # explicit dtype
        b = np.asarray(host_arr)                 # dtype-preserving
        c = np.asarray(np.array([1, 2]), np.int32)  # converted right above
        d = np.asarray(jax.device_get(x))        # device pull, keeps dtype
        return a, b, c, d
    """
    assert "G005" not in _codes(src, path=HOT)


# -- G006: retrace storms --------------------------------------------------

def test_g006_triggers_on_high_cardinality_static():
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("seed",))
    def f(x, seed):
        return x + seed
    """
    assert "G006" in _codes(src)


def test_g006_clean_on_module_level_jit_with_shape_statics():
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("topic_mode",))
    def f(x, topic_mode):
        return x

    f_jit = jax.jit(f, static_argnames=("topic_mode",))
    """
    assert "G006" not in _codes(src)


# -- G010: jit wrapper created inside a function body ----------------------

def test_g010_triggers_on_jit_inside_function_body():
    src = """
    import jax

    def make_step(scale):
        return jax.jit(lambda x: x * scale)
    """
    assert "G010" in _codes(src)


def test_g010_triggers_on_partial_jit_inside_function_body():
    src = """
    import jax
    from functools import partial

    def make_step(scale):
        step = partial(jax.jit, donate_argnums=(0,))(lambda x: x * scale)
        return step
    """
    assert "G010" in _codes(src)


def test_g010_triggers_on_decorated_nested_def():
    src = """
    import jax

    def outer(y):
        @jax.jit
        def inner(x):
            return x + y
        return inner
    """
    assert "G010" in _codes(src)


def test_g010_clean_on_module_level_wrappers():
    src = """
    import jax
    from functools import partial

    @jax.jit
    def f(x):
        return x

    g = jax.jit(lambda x: x + 1)
    h = partial(jax.jit, static_argnames=("mode",))(f)
    """
    assert "G010" not in _codes(src)


def test_g010_inline_suppression():
    src = """
    import jax

    def warmup():
        jax.jit(lambda x: x + 1)(1.0)  # graftlint: disable=G010
    """
    assert "G010" not in _codes(src)


# -- G011: raw wall-clock in control-plane paths ---------------------------

CONTROL = "cruise_control_tpu/executor/somefile.py"


def test_g011_triggers_on_raw_time_and_sleep_in_control_path():
    src = """
    import time

    def poll():
        t = time.time()
        time.sleep(1.0)
        return t
    """
    assert _codes(src, path=CONTROL).count("G011") == 2


def test_g011_scopes_to_control_plane_paths():
    src = """
    import time

    def poll():
        return time.time()
    """
    # analyzer/ (and anything outside app/executor/monitor/detector/
    # replication) is out of scope — the clock seam contract covers the
    # control loop only
    assert "G011" not in _codes(
        src, path="cruise_control_tpu/analyzer/somefile.py")
    assert "G011" in _codes(src, path="cruise_control_tpu/app.py")
    assert "G011" in _codes(
        src, path="cruise_control_tpu/monitor/somefile.py")
    assert "G011" in _codes(
        src, path="cruise_control_tpu/detector/somefile.py")
    # lease/takeover timing must ride the injected clock seam too
    assert "G011" in _codes(
        src, path="cruise_control_tpu/replication/somefile.py")


def test_g011_clean_on_seam_references_and_injected_clock():
    src = """
    import time

    class Executor:
        def __init__(self, clock=time.time, sleep=time.sleep):
            self._clock = clock
            self._sleep = sleep

        def poll(self):
            t = self._clock()
            self._sleep(0.1)
            return t
    """
    # references plumb the seam; only raw CALLS bypass it
    assert "G011" not in _codes(src, path=CONTROL)


def test_g011_inline_suppression():
    src = """
    import time

    def wall_deadline():
        return time.time() + 5  # graftlint: disable=G011
    """
    assert "G011" not in _codes(src, path=CONTROL)


# -- G008: forbidden impurity inside jit -----------------------------------

def test_g008_triggers_on_host_rng_time_and_print():
    src = """
    import jax
    import numpy as np
    import time

    @jax.jit
    def f(x):
        print(x)
        t = time.time()
        return x + np.random.rand() + t
    """
    codes = _codes(src)
    assert codes.count("G008") == 3


def test_g008_clean_on_jax_rng_and_debug_print():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x, key):
        jax.debug.print("x={x}", x=x)
        return x + jax.random.normal(key, x.shape)
    """
    assert "G008" not in _codes(src)


# -- G009: silent broad except ---------------------------------------------

def test_g009_triggers_on_bare_except_pass():
    src = """
    def f():
        try:
            risky()
        except:
            pass
    """
    assert "G009" in _codes(src)


def test_g009_triggers_on_swallowed_exception():
    src = """
    def f():
        out = []
        try:
            out.append(compute())
        except Exception:
            out = None
        return out
    """
    assert "G009" in _codes(src)


def test_g009_triggers_inside_tuple_handler():
    src = """
    def f():
        try:
            risky()
        except (ValueError, Exception):
            return None
    """
    assert "G009" in _codes(src)


def test_g009_clean_on_logging_reraise_and_narrow():
    src = """
    import logging
    logger = logging.getLogger(__name__)

    def f():
        try:
            risky()
        except Exception:
            logger.warning("risky failed", exc_info=True)
        try:
            risky()
        except Exception:
            raise RuntimeError("wrapped")
        try:
            risky()
        except ValueError:
            return None
    """
    assert "G009" not in _codes(src)


def test_g009_clean_with_inline_disable():
    src = """
    def close(producer):
        try:
            producer.close()
        except Exception:  # graftlint: disable=G009
            pass
    """
    assert "G009" not in _codes(src)


# -- G007: unwired config keys (project rule, real package) ----------------

def test_g007_whole_package_has_no_unwired_keys():
    """Generalizes test_no_silently_unwired_key into the lint framework:
    the project rule must run AND report nothing on the real package."""
    findings = lint(["cruise_control_tpu"], select=["G007"],
                    with_project_rules=True)
    assert findings == [], "\n".join(f.format() for f in findings)


# -- G101: unguarded shared-attribute access -------------------------------

def test_g101_triggers_on_unguarded_read_and_write():
    src = """
    import threading

    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, x):
            with self._lock:
                self._items.append(x)

        def size(self):
            return len(self._items)        # read outside the lock

        def reset(self):
            self._items = []               # write outside the lock
    """
    assert _codes(src).count("G101") == 2


def test_g101_clean_with_cross_method_inference():
    # _grow mutates the guarded list but is ONLY called with the lock held
    # — the cross-method fixpoint must treat its body as lock-held (the
    # aggregator._row/_slot/_roll pattern)
    src = """
    import threading

    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, x):
            with self._lock:
                self._grow(x)

        def _grow(self, x):
            self._items.append(x)

        def size(self):
            with self._lock:
                return len(self._items)
    """
    assert "G101" not in _codes(src)


def test_g101_clean_with_inline_disable_and_init_exempt():
    src = """
    import threading

    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []           # construction: happens-before, exempt

        def add(self, x):
            with self._lock:
                self._items.append(x)

        def size(self):
            return len(self._items)    # graftlint: disable=G101
    """
    assert "G101" not in _codes(src)


# -- G102: lock-order cycles (project rule) --------------------------------

def test_g102_triggers_on_opposite_acquisition_orders(tmp_path):
    (tmp_path / "m.py").write_text(textwrap.dedent("""
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def f():
        with A:
            with B:
                pass

    def g():
        with B:
            with A:
                pass
    """))
    findings = lint([str(tmp_path / "m.py")], select=["G102"],
                    root=str(tmp_path), with_project_rules=True)
    assert [f.code for f in findings] == ["G102", "G102"]


def test_g102_clean_on_consistent_order(tmp_path):
    (tmp_path / "m.py").write_text(textwrap.dedent("""
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def f():
        with A:
            with B:
                pass

    def g():
        with A:
            with B:
                pass
    """))
    findings = lint([str(tmp_path / "m.py")], select=["G102"],
                    root=str(tmp_path), with_project_rules=True)
    assert findings == []


def test_g102_multi_item_with_records_acquisition_order(tmp_path):
    """`with A, B:` acquires B while holding A — one statement, same edge
    as nested withs; an opposite-order path elsewhere is still a cycle."""
    (tmp_path / "m.py").write_text(textwrap.dedent("""
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def f():
        with A, B:
            pass

    def g():
        with B:
            with A:
                pass
    """))
    findings = lint([str(tmp_path / "m.py")], select=["G102"],
                    root=str(tmp_path), with_project_rules=True)
    assert [f.code for f in findings] == ["G102", "G102"]


# -- G103: background thread without a shutdown path -----------------------

def test_g103_triggers_on_fire_and_forget_and_unjoined():
    src = """
    import threading

    def kick(fn):
        threading.Thread(target=fn, daemon=True).start()

    class Svc:
        def start(self, fn):
            self._thread = threading.Thread(target=fn, daemon=True)
            self._thread.start()
    """
    assert _codes(src).count("G103") == 2


def test_g103_clean_on_event_join_pair():
    src = """
    import threading

    class Svc:
        def start(self, fn):
            self._shutdown = threading.Event()
            self._thread = threading.Thread(target=fn, daemon=True)
            self._thread.start()

        def close(self):
            self._shutdown.set()
            self._thread.join(timeout=5)

    def run_sync(fn):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    """
    assert "G103" not in _codes(src)


# -- G104: check-then-act outside the lock ---------------------------------

def test_g104_triggers_on_unlocked_check_then_act():
    src = """
    import threading

    class Svc:
        def __init__(self):
            self._lock = threading.Lock()
            self._worker = None

        def _set(self, w):
            with self._lock:
                self._worker = w

        def ensure(self, w):
            if self._worker is None:   # racy: another thread can win
                self._worker = w
    """
    assert "G104" in _codes(src)


def test_g104_clean_when_locked():
    src = """
    import threading

    class Svc:
        def __init__(self):
            self._lock = threading.Lock()
            self._worker = None

        def _set(self, w):
            with self._lock:
                self._worker = w

        def ensure(self, w):
            with self._lock:
                if self._worker is None:
                    self._worker = w
    """
    assert "G104" not in _codes(src)


# -- G105: blocking call while a lock is held ------------------------------

def test_g105_triggers_on_sleep_and_result_under_lock():
    src = """
    import threading
    import time

    class Svc:
        def __init__(self):
            self._lock = threading.Lock()

        def tick(self, future):
            with self._lock:
                time.sleep(0.5)
                return future.result()
    """
    assert _codes(src).count("G105") == 2


def test_g105_clean_outside_lock_and_snapshot_idiom():
    src = """
    import threading
    import time

    class Svc:
        def __init__(self):
            self._lock = threading.Lock()
            self._pending = []

        def tick(self, future):
            with self._lock:
                batch = list(self._pending)
            time.sleep(0.5)            # outside the critical section
            return batch, future.result()
    """
    assert "G105" not in _codes(src)


def test_g105_clean_on_domain_object_result_and_wait():
    """`.result()`/`.wait()` only count when the receiver NAME suggests a
    synchronization object — a domain object's methods of the same name
    (an HTTP response's .result(), a process proxy's .wait()) don't flag."""
    src = """
    import threading

    class Svc:
        def __init__(self):
            self._lock = threading.Lock()

        def tick(self, response, fut):
            with self._lock:
                summary = response.result()    # domain .result(): clean
                self.handle.wait()             # domain .wait(): clean
                return summary, fut.result()   # future: still flagged
    """
    assert _codes(src).count("G105") == 1


# -- baseline mechanics ----------------------------------------------------

def test_baseline_suppresses_exact_count_and_flags_growth(tmp_path):
    src = textwrap.dedent("""
    import jax.numpy as jnp

    def f(n):
        out = []
        for i in range(n):
            out.append(jnp.zeros(4))
            out.append(jnp.ones(4))
        return out
    """)
    findings = lint_source(src, path="cruise_control_tpu/x.py")
    g3 = [f for f in findings if f.code == "G003"]
    assert len(g3) == 2
    baseline = {g3[0].fingerprint: {"fingerprint": g3[0].fingerprint,
                                    "count": 1, "justification": "test"}}
    new, suppressed, stale = apply_baseline(g3, baseline)
    # zeros suppressed, ones is new
    assert len(suppressed) == 1 and len(new) == 1
    assert stale == []
    # fingerprints are line-free: shifting the file must not churn them
    shifted = lint_source("\n\n\n" + src, path="cruise_control_tpu/x.py")
    assert ([f.fingerprint for f in findings]
            == [f.fingerprint for f in shifted])


def test_prune_stale_drops_dead_entries_preserving_live(tmp_path):
    import json
    live = LE.Finding("G003", "cruise_control_tpu/x.py", 3, 0, "m",
                      snippet="jnp.zeros(4)")
    path = tmp_path / "b.json"
    path.write_text(json.dumps({"version": 1, "suppressions": [
        {"fingerprint": live.fingerprint, "count": 2, "line": 3,
         "justification": "keep me verbatim"},
        {"fingerprint": "G003|cruise_control_tpu/gone.py|old()", "count": 1,
         "line": 9, "justification": "dead"},
        {"fingerprint": "G101|cruise_control_tpu/gone.py|old()", "count": 1,
         "line": 9, "justification": "dead, other code"},
    ]}))
    kept, dropped = LE.prune_stale_baseline([live], path=str(path))
    assert kept == 1 and len(dropped) == 2
    after = load_baseline(str(path))
    # the live entry survives VERBATIM — count and justification untouched
    assert after[live.fingerprint]["count"] == 2
    assert after[live.fingerprint]["justification"] == "keep me verbatim"


def test_prune_stale_scoped_to_selected_codes(tmp_path):
    """A --rules-filtered run must not drop entries its rules never
    produced: pruning with codes={G101} leaves the stale G003 alone."""
    import json
    path = tmp_path / "b.json"
    path.write_text(json.dumps({"version": 1, "suppressions": [
        {"fingerprint": "G003|cruise_control_tpu/gone.py|old()", "count": 1,
         "line": 9, "justification": "stale but out of scope"},
        {"fingerprint": "G101|cruise_control_tpu/gone.py|old()", "count": 1,
         "line": 9, "justification": "stale and in scope"},
    ]}))
    kept, dropped = LE.prune_stale_baseline([], path=str(path),
                                            codes={"G101"})
    assert kept == 1
    assert dropped == ["G101|cruise_control_tpu/gone.py|old()"]
    # the rewritten FILE must still hold the out-of-scope entry: it is
    # neither live (its rule never ran) nor dropped (codes filter)
    after = load_baseline(str(path))
    assert set(after) == {"G003|cruise_control_tpu/gone.py|old()"}
    assert (after["G003|cruise_control_tpu/gone.py|old()"]["justification"]
            == "stale but out of scope")


def test_cli_rules_filter(capsys):
    """--rules is the --select alias: a G103-only run over rest.py sees
    exactly the baselined serve_forever thread — exit 0 with the baseline,
    exit 1 without it."""
    assert LE.main(["--rules", "G103", "--no-project-rules",
                    "cruise_control_tpu/server/rest.py"]) == 0
    assert LE.main(["--rules", "G103", "--no-project-rules", "--no-baseline",
                    "cruise_control_tpu/server/rest.py"]) == 1
    out = capsys.readouterr().out
    assert "G103" in out


# -- the tentpole gate -----------------------------------------------------

def test_package_lints_clean_against_baseline():
    """`python -m tools.graftlint cruise_control_tpu bench.py` is clean:
    every finding in the repo is either fixed or baselined with a
    justification.  New hazards fail HERE."""
    findings = lint(["cruise_control_tpu", "bench.py"], root=LE.REPO_ROOT,
                    with_project_rules=True)
    baseline = load_baseline()
    new, _suppressed, stale = apply_baseline(findings, baseline)
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f.format() for f in new)
    # zero stale entries: a fixed finding must take its suppression with it
    # (python -m tools.graftlint --prune-stale drops them)
    assert stale == [], "stale baseline entries (run --prune-stale):\n" + \
        "\n".join(stale)
    for entry in baseline.values():
        assert entry.get("justification", "").strip() not in (
            "", "TODO: justify or fix"), (
            f"baseline entry lacks a real justification: "
            f"{entry['fingerprint']}")
    # the provisioner package shipped lint-clean: no suppression may ever
    # point into it (fingerprints embed the path — G001–G105 all enforced)
    prov = [fp for fp in baseline
            if fp.split("|")[1].startswith("cruise_control_tpu/provisioner/")]
    assert prov == [], f"provisioner package must stay baseline-free: {prov}"
    # the incremental tick path (device window kernels + analyzer rescore)
    # also shipped lint-clean — same standing gate
    incr = [fp for fp in baseline
            if fp.split("|")[1] in ("cruise_control_tpu/ops/windows.py",
                                    "cruise_control_tpu/analyzer/rescore.py")]
    assert incr == [], f"incremental tick path must stay baseline-free: {incr}"
    # the self-healing kernels (annealer propose-mask lowering + repair
    # fused shed ladder) also shipped lint-clean: no suppression may name
    # them, by fingerprint path or by snippet content
    heal = [fp for fp, entry in baseline.items()
            if "_fused_shed" in json.dumps(entry)
            or "propose_dest_mask" in json.dumps(entry)]
    assert heal == [], (
        f"self-heal kernels must stay baseline-free: {heal}")
    # the multi-device sharding layer (compat shim, mesh policy, shard_map
    # kernels) shipped lint-clean — scale-out code answers to every rule
    par = [fp for fp in baseline
           if fp.split("|")[1].startswith("cruise_control_tpu/parallel/")]
    assert par == [], f"parallel package must stay baseline-free: {par}"
    # the scenario simulator shipped lint-clean — no suppression may point
    # into it, by fingerprint path or by snippet content
    sim = [fp for fp, entry in baseline.items()
           if fp.split("|")[1].startswith("cruise_control_tpu/simulator/")
           or "SimulatedKafkaCluster" in json.dumps(entry)
           or "FaultSchedule" in json.dumps(entry)]
    assert sim == [], f"simulator package must stay baseline-free: {sim}"
    # the replicated control plane (lease, shipper/tailer, warm standby)
    # shipped lint-clean under G001–G011 — in particular G011: lease
    # timing routes through the injected now_ms seam, never raw
    # time.time(). No suppression may point into it, by fingerprint path
    # or by snippet content.
    repl = [fp for fp, entry in baseline.items()
            if fp.split("|")[1].startswith("cruise_control_tpu/replication/")
            or "LeaderLease" in json.dumps(entry)
            or "WarmStandby" in json.dumps(entry)]
    assert repl == [], f"replication package must stay baseline-free: {repl}"
    # the anneal hot-path cuts (warm-started chains, device-side proposal
    # decode) shipped lint-clean — no suppression may name them, by
    # snippet content (the code lives in pre-existing files, so a path
    # gate would over-match)
    raw = [fp for fp, entry in baseline.items()
           if "WarmStart" in json.dumps(entry)
           or "LazyProposals" in json.dumps(entry)
           or "device_diff" in json.dumps(entry)
           or "_diff_kernel" in json.dumps(entry)]
    assert raw == [], f"warm-start/device-decode code must stay baseline-free: {raw}"


# -- runtime sentinels -----------------------------------------------------

def test_transfer_guard_semantics():
    """The guard underlying the annealer's steady-state scope: explicit
    transfers pass, implicit ones raise."""
    from cruise_control_tpu.common import sentinels as SENT
    x = jnp.arange(4, dtype=jnp.float32)
    host = np.ones(4, np.float32)
    with SENT.no_implicit_transfers():
        jax.device_get(x)            # explicit pull: allowed
        jnp.asarray(host)            # explicit upload: allowed
        with pytest.raises(Exception):
            _ = x + 1.0              # implicit scalar upload: blocked


def test_steady_state_anneal_zero_retraces_under_guard():
    """The acceptance-criteria sentinel, CPU-tier: a warmed second
    optimize (anneal engine, so the `_run_pt` transfer_guard scope is
    exercised) performs ZERO retraces not covered by the runtime
    baseline.  Must warm and measure inside ONE test (conftest clears jax
    caches between tests)."""
    from cruise_control_tpu.analyzer import annealer as AN
    from cruise_control_tpu.analyzer import optimizer as OPT
    from cruise_control_tpu.common import sentinels as SENT
    from cruise_control_tpu.models import fixtures

    topo, assign = fixtures.synthetic_cluster(
        num_brokers=12, num_replicas=400, num_racks=3, rf=3,
        num_topics=20, seed=0)
    cfg = AN.AnnealConfig(num_chains=4, steps=128, swap_interval=64,
                          tries_move=16, tries_lead=4, tries_swap=8)
    kw = dict(engine="anneal", anneal_config=cfg, seed=0)
    OPT.optimize(topo, assign, **kw)                 # compile + warm
    with SENT.retrace_sentinel() as log:
        OPT.optimize(topo, assign, **kw)             # steady state
    uncovered = SENT.check_steady_state(log, strict=False)
    assert uncovered == [], (
        f"warmed steady-state optimize retraced: {log.summary()} — either "
        f"fix the retrace or add it to tools/graftlint/"
        f"runtime_baseline.json with a justification")
