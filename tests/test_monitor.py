"""Monitor subsystem tests, modeled on the reference's
MetricSampleAggregatorTest / LoadMonitorTest patterns: window rolling,
extrapolation, completeness gating, capacity resolution, end-to-end model
building from a fake metadata source + synthetic sampler.
"""

import json
import os
import time

import numpy as np
import pytest

from cruise_control_tpu.common import resources as res
from cruise_control_tpu.models.cluster import derive_follower_load
from cruise_control_tpu.monitor import metricdef as md
from cruise_control_tpu.monitor.aggregator import (
    MetricSampleAggregator,
    ModelCompletenessRequirements,
)
from cruise_control_tpu.monitor.capacity import (
    FileCapacityResolver,
    StaticCapacityResolver,
)
from cruise_control_tpu.monitor.load_monitor import (
    LoadMonitor,
    MonitorState,
    NotEnoughValidWindowsError,
    StaticMetadataSource,
)
from cruise_control_tpu.monitor.sample_store import FileSampleStore
from cruise_control_tpu.monitor.sampler import (
    BrokerMetadata,
    ClusterMetadata,
    PartitionMetadata,
    PartitionMetricSample,
    SyntheticLoadSampler,
)

W = 60_000  # window ms


def _sample(topic, part, t, nw_in=100.0, disk=50.0):
    m = np.full(md.NUM_MODEL_METRICS, np.nan)
    m[md.ModelMetric.LEADER_BYTES_IN] = nw_in
    m[md.ModelMetric.DISK_USAGE] = disk
    return (topic, part), t, m


def test_aggregator_windows_and_strategies():
    agg = MetricSampleAggregator(num_windows=3, window_ms=W,
                                 min_samples_per_window=1)
    e = ("t", 0)
    # window 0: two samples -> AVG averages, LATEST takes newest
    agg.add_sample(e, 10_000, _sample("t", 0, 10_000, nw_in=100.0, disk=10.0)[2], group="t")
    agg.add_sample(e, 20_000, _sample("t", 0, 20_000, nw_in=200.0, disk=30.0)[2], group="t")
    # windows 1, 2
    agg.add_sample(e, W + 5_000, _sample("t", 0, W + 5_000, nw_in=300.0, disk=40.0)[2], group="t")
    agg.add_sample(e, 2 * W + 5_000, _sample("t", 0, 2 * W + 5_000, nw_in=400.0, disk=50.0)[2], group="t")
    r = agg.aggregate(now_ms=3 * W)
    assert r.completeness.num_valid_windows == 3
    assert len(r.entities) == 1
    v = r.values[0]  # [W=3, M]
    assert v[0, md.ModelMetric.LEADER_BYTES_IN] == pytest.approx(150.0)  # AVG
    assert v[0, md.ModelMetric.DISK_USAGE] == pytest.approx(30.0)        # LATEST
    assert v[1, md.ModelMetric.LEADER_BYTES_IN] == pytest.approx(300.0)
    assert v[2, md.ModelMetric.LEADER_BYTES_IN] == pytest.approx(400.0)


def test_aggregator_avg_adjacent_extrapolation():
    agg = MetricSampleAggregator(num_windows=3, window_ms=W,
                                 min_samples_per_window=1)
    e = ("t", 0)
    agg.add_sample(e, 5_000, _sample("t", 0, 5_000, nw_in=100.0)[2], group="t")
    # window 1 empty
    agg.add_sample(e, 2 * W + 5_000, _sample("t", 0, 0, nw_in=300.0)[2], group="t")
    r = agg.aggregate(now_ms=3 * W)
    assert len(r.entities) == 1
    v = r.values[0]
    # middle window borrowed from neighbors: (100+300)/2
    assert v[1, md.ModelMetric.LEADER_BYTES_IN] == pytest.approx(200.0)
    assert r.extrapolations[0, 1] == 2  # AVG_ADJACENT


def test_aggregator_invalid_entity_dropped():
    agg = MetricSampleAggregator(num_windows=3, window_ms=W,
                                 min_samples_per_window=1)
    # entity with only one sample in the first of 3 windows -> two empty
    # windows in a row cannot extrapolate -> entity invalid
    agg.add_sample(("t", 0), 5_000, _sample("t", 0, 0)[2], group="t")
    # a healthy entity with samples in all windows
    for w in range(3):
        agg.add_sample(("t", 1), w * W + 5_000, _sample("t", 1, 0)[2], group="t")
    r = agg.aggregate(now_ms=3 * W)
    assert r.entities == [("t", 1)]
    assert r.completeness.valid_entity_ratio == pytest.approx(0.5)


def test_aggregator_window_rolling_drops_oldest():
    agg = MetricSampleAggregator(num_windows=2, window_ms=W,
                                 min_samples_per_window=1)
    e = ("t", 0)
    agg.add_sample(e, 5_000, _sample("t", 0, 0, nw_in=1.0)[2], group="t")
    gen0 = agg.generation
    # jump 5 windows ahead: the old window cycles out, generation bumps
    agg.add_sample(e, 5 * W + 5_000, _sample("t", 0, 0, nw_in=5.0)[2], group="t")
    assert agg.generation > gen0
    r = agg.aggregate(now_ms=6 * W)
    # honest per-window accounting (MetricSampleCompleteness): of the two
    # completed windows [4, 5], only window 5 has data → 1 valid window
    assert r.completeness.num_valid_windows == 1
    assert list(r.completeness.valid_entity_ratio_per_window) == [0.0, 1.0]


def test_aggregator_gap_does_not_alias_stale_windows():
    """After a sampling gap longer than the buffer, expired slots must not
    leak old samples into new window indexes (stale-slot aliasing)."""
    agg = MetricSampleAggregator(num_windows=3, window_ms=W,
                                 min_samples_per_window=1)
    e = ("t", 0)
    for w in range(4):
        agg.add_sample(e, w * W + 5_000, _sample("t", 0, 0, nw_in=9.0)[2],
                       group="t")
    # no samples since; aggregate far in the future: every completed window
    # in [cur-3, cur) is empty, so nothing may be attributed
    r = agg.aggregate(now_ms=50 * W)
    assert r.completeness.num_valid_windows == 0
    assert r.completeness.num_valid_entities == 0


def test_capacity_file_resolver_formats(tmp_path):
    plain = {"brokerCapacities": [
        {"brokerId": "-1", "capacity": {"DISK": "100000", "CPU": "100",
                                        "NW_IN": "10000", "NW_OUT": "10000"}},
        {"brokerId": "0", "capacity": {"DISK": "500000", "CPU": "100",
                                       "NW_IN": "50000", "NW_OUT": "50000"}},
    ]}
    p = tmp_path / "capacity.json"
    p.write_text(json.dumps(plain))
    r = FileCapacityResolver(str(p))
    assert r.capacity_for_broker(0).capacity[res.DISK] == 500000
    assert r.capacity_for_broker(7).capacity[res.DISK] == 100000  # default

    jbod = {"brokerCapacities": [
        {"brokerId": "-1", "capacity": {
            "DISK": {"/d1": "100000", "/d2": "50000"},
            "CPU": "100", "NW_IN": "10000", "NW_OUT": "10000"}},
    ]}
    p2 = tmp_path / "capacityJBOD.json"
    p2.write_text(json.dumps(jbod))
    r2 = FileCapacityResolver(str(p2))
    info = r2.capacity_for_broker(3)
    assert info.is_jbod
    assert info.capacity[res.DISK] == 150000
    assert info.disk_capacity_by_logdir == {"/d1": 100000.0, "/d2": 50000.0}

    cores = {"brokerCapacities": [
        {"brokerId": "-1", "num.cores": "8",
         "capacity": {"DISK": "100000", "NW_IN": "10000", "NW_OUT": "10000"}},
    ]}
    p3 = tmp_path / "capacityCores.json"
    p3.write_text(json.dumps(cores))
    assert FileCapacityResolver(str(p3)).capacity_for_broker(0).capacity[res.CPU] == 800.0


def _metadata(num_brokers=4, num_parts=8, rf=2, dead=()):
    brokers = [BrokerMetadata(i, rack=f"r{i % 2}", host=f"h{i}",
                              alive=i not in dead)
               for i in range(num_brokers)]
    parts = []
    for p in range(num_parts):
        reps = tuple((p + j) % num_brokers for j in range(rf))
        parts.append(PartitionMetadata(topic="T", partition=p,
                                       leader=reps[0], replicas=reps))
    return ClusterMetadata(brokers=brokers, partitions=parts, generation=1)


def _filled_monitor(metadata, windows=3):
    lm = LoadMonitor(StaticMetadataSource(metadata), SyntheticLoadSampler(seed=5),
                     num_windows=windows, window_ms=W)
    for w in range(windows + 1):
        lm.sample_once(now_ms=w * W + 30_000)
    return lm


def test_load_monitor_builds_model():
    metadata = _metadata()
    lm = _filled_monitor(metadata)
    topo, assign = lm.cluster_model(
        now_ms=4 * W,
        requirements=ModelCompletenessRequirements(min_required_num_windows=2))
    assert topo.num_brokers == 4
    assert topo.num_partitions == 8
    assert topo.num_replicas == 16
    # follower load derivation: follower NW_OUT must be 0
    from cruise_control_tpu.ops.aggregates import device_topology
    from cruise_control_tpu.ops.stats import sanity_check
    dt = device_topology(topo)
    checks = sanity_check(dt, assign, topo.num_topics)
    assert all(checks.values()), checks
    is_leader = np.zeros(topo.num_replicas, bool)
    is_leader[np.asarray(assign.leader_of)] = True
    assert (topo.replica_base_load[~is_leader][:, res.NW_OUT] >= 0).all()


def test_load_monitor_dead_broker_offline_replicas():
    metadata = _metadata(dead=(1,))
    lm = _filled_monitor(metadata)
    topo, assign = lm.cluster_model(now_ms=4 * W)
    assert not topo.broker_alive[[b == 1 for b in topo.broker_ids]].any()
    on_dead = np.asarray(assign.broker_of) == list(topo.broker_ids).index(1)
    assert topo.replica_offline[on_dead].all()


def test_load_monitor_completeness_gate():
    metadata = _metadata()
    lm = LoadMonitor(StaticMetadataSource(metadata), SyntheticLoadSampler(),
                     num_windows=5, window_ms=W)
    lm.sample_once(now_ms=30_000)
    with pytest.raises(NotEnoughValidWindowsError):
        lm.cluster_model(
            now_ms=W,
            requirements=ModelCompletenessRequirements(min_required_num_windows=3))


def test_load_monitor_pause_resume_state():
    lm = LoadMonitor(StaticMetadataSource(_metadata()), SyntheticLoadSampler())
    assert lm.state == MonitorState.NOT_STARTED
    lm._state = MonitorState.RUNNING
    lm.pause("maintenance")
    assert lm.state == MonitorState.PAUSED
    lm.resume("done")
    assert lm.state == MonitorState.RUNNING
    snap = lm.state_snapshot(now_ms=W)
    assert snap["state"] == "RUNNING"


def test_sample_store_roundtrip(tmp_path):
    store = FileSampleStore(str(tmp_path))
    metadata = _metadata()
    sampler = SyntheticLoadSampler(seed=5)
    ps, bs = sampler.get_samples(metadata, 0, W)
    store.store_samples(ps, bs)
    got_p, got_b = [], []
    n = store.load_samples(got_p.append, got_b.append)
    assert n == len(ps) + len(bs)
    assert got_p[0].topic == ps[0].topic
    np.testing.assert_allclose(got_p[0].metrics, ps[0].metrics)
    assert got_b[0].broker_id == bs[0].broker_id


class _FakeKafkaBroker:
    """In-memory topic log shared by producer/consumer fakes (the
    fake-broker pattern of tests/test_kafka_adapter.py)."""

    def __init__(self):
        self.topics = {}
        self.created = []

    # admin
    def create_topics(self, new_topics):
        for t in new_topics:
            self.created.append((t.name, t.num_partitions,
                                 t.replication_factor, dict(t.topic_configs)))
            self.topics.setdefault(t.name, [])

    # producer
    def send(self, topic, value, key=None):
        self.topics.setdefault(topic, []).append((key, value))

    def flush(self):
        pass

    def close(self):
        pass

    # consumer
    def consumer(self, topic):
        import types as _types
        return iter([_types.SimpleNamespace(value=json.dumps(v))
                     for _, v in self.topics.get(topic, [])])


def test_kafka_sample_store_replay_roundtrip():
    """store → service restart → replay (KafkaSampleStore.java:317,355):
    a fresh LoadMonitor over a fresh KafkaSampleStore bound to the same
    (fake) cluster must rebuild the aggregator state and serve a model
    equal to the pre-restart one."""
    from cruise_control_tpu.monitor.sample_store import KafkaSampleStore
    broker = _FakeKafkaBroker()

    def make_store():
        return KafkaSampleStore(producer=broker,
                                consumer_factory=broker.consumer,
                                admin=broker)

    metadata = _metadata(num_brokers=6, num_parts=40, rf=2)
    store1 = make_store()
    # topic bootstrap happened with the configured partition counts
    assert {c[0] for c in broker.created} == {
        KafkaSampleStore.PARTITION_TOPIC, KafkaSampleStore.BROKER_TOPIC}
    lm1 = LoadMonitor(StaticMetadataSource(metadata),
                      SyntheticLoadSampler(seed=5), num_windows=3,
                      window_ms=W, sample_store=store1)
    for w in range(4):
        lm1.sample_once(now_ms=w * W + 30_000)
    topo1, assign1 = lm1.cluster_model(now_ms=4 * W)

    # "restart": a new monitor + store over the same cluster, replay only
    store2 = make_store()
    lm2 = LoadMonitor(StaticMetadataSource(metadata),
                      SyntheticLoadSampler(seed=99), num_windows=3,
                      window_ms=W, sample_store=store2)
    lm2.startup(load_stored_samples=True)
    lm2.shutdown()
    topo2, assign2 = lm2.cluster_model(now_ms=4 * W)
    np.testing.assert_allclose(np.asarray(topo2.replica_base_load),
                               np.asarray(topo1.replica_base_load),
                               rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(assign2.broker_of),
                                  np.asarray(assign1.broker_of))


def test_kafka_sample_store_skips_corrupt_records():
    """Corrupt records must not abort the replay
    (KafkaSampleStore.java loadSamples swallows deserialization errors)."""
    from cruise_control_tpu.monitor.sample_store import KafkaSampleStore
    broker = _FakeKafkaBroker()
    store = KafkaSampleStore(producer=broker,
                             consumer_factory=broker.consumer, admin=broker)
    metadata = _metadata()
    ps, bs = SyntheticLoadSampler(seed=5).get_samples(metadata, 0, W)
    store.store_samples(ps, bs)
    # inject garbage between valid records
    broker.topics[store.partition_topic].insert(1, (b"x", "not json"))
    broker.topics[store.broker_topic].insert(0, (b"y", {"no": "fields"}))
    got_p, got_b = [], []
    n = store.load_samples(got_p.append, got_b.append)
    assert n == len(ps) + len(bs)
    assert len(got_p) == len(ps) and len(got_b) == len(bs)


def test_monitor_to_optimizer_end_to_end():
    """Full slice: metadata + synthetic samples -> model -> optimization."""
    from cruise_control_tpu.analyzer import optimizer as OPT
    metadata = _metadata(num_brokers=6, num_parts=40, rf=2)
    lm = _filled_monitor(metadata)
    topo, assign = lm.cluster_model(now_ms=4 * W)
    r = OPT.optimize(topo, assign)
    assert r.balancedness_after >= r.balancedness_before
    hard = [s for s in r.goal_summaries if s.hard]
    assert all(s.violations_after == 0 for s in hard)


def test_train_linear_regression_cpu_model():
    """TRAIN fits LinearRegressionModelParameters-style coefficients from
    broker samples and partition CPU estimation switches to them."""
    from cruise_control_tpu.kafka_adapter import process_raw_metrics
    from cruise_control_tpu.models.cluster import LinearRegressionCpuModel
    from cruise_control_tpu.monitor.load_monitor import LoadMonitor, StaticMetadataSource
    from cruise_control_tpu.monitor.sampler import (BrokerMetricSample,
                                                    MetricSampler,
                                                    PartitionMetricSample)

    true_w = (2.0e-8, 1.0e-8, 5.0e-9)

    class CpuSampler(MetricSampler):
        """Broker samples whose CPU is an exact linear function of rates."""
        def __init__(self):
            self.cpu_model = None
            self.rng = np.random.default_rng(5)

        def set_cpu_model(self, m):
            self.cpu_model = m

        def get_samples(self, metadata, start_ms, end_ms):
            bs = []
            for b in range(6):
                lbi = float(self.rng.uniform(1e6, 5e7))
                lbo = float(self.rng.uniform(1e6, 5e7))
                fbi = float(self.rng.uniform(1e5, 1e7))
                cpu = true_w[0] * lbi + true_w[1] * lbo + true_w[2] * fbi
                bs.append(BrokerMetricSample(
                    broker_id=b, time_ms=(start_ms + end_ms) // 2,
                    cpu_util=cpu, leader_bytes_in=lbi, leader_bytes_out=lbo,
                    replication_bytes_in=fbi))
            return [], bs

    md_src = StaticMetadataSource(_metadata())
    sampler = CpuSampler()
    lm = LoadMonitor(md_src, sampler, num_windows=3, window_ms=W,
                     use_lr_model=True)
    result = lm.train(0, 5 * W)
    assert result["trained"] is True
    assert lm.cpu_model.trained and lm.cpu_model.num_samples >= 18
    np.testing.assert_allclose(
        [lm.cpu_model.coef_leader_bytes_in, lm.cpu_model.coef_leader_bytes_out,
         lm.cpu_model.coef_follower_bytes_in], true_w, rtol=1e-4)
    # trained model installed into the sampler (use.linear.regression.model)
    assert sampler.cpu_model is lm.cpu_model
    assert lm.state_snapshot(now_ms=5 * W)["trained"] is True

    # partition CPU estimation switches to the trained coefficients
    from cruise_control_tpu.monitor.sampler import ClusterMetadata, PartitionMetadata, BrokerMetadata
    from cruise_control_tpu.reporter import CruiseControlMetric
    meta = ClusterMetadata(
        brokers=[BrokerMetadata(0, rack="r0", host="h0")],
        partitions=[PartitionMetadata("T", 0, leader=0, replicas=(0,))],
        generation=1)
    raw = [CruiseControlMetric("TOPIC_BYTES_IN", 1000, 0, 1e6, topic="T"),
           CruiseControlMetric("TOPIC_BYTES_OUT", 1000, 0, 2e6, topic="T"),
           CruiseControlMetric("BROKER_CPU_UTIL", 1000, 0, 50.0)]
    ps_static, _ = process_raw_metrics(raw, meta, 1000)
    ps_lr, _ = process_raw_metrics(raw, meta, 1000, cpu_model=lm.cpu_model)
    import numpy as _np
    cpu_static = ps_static[0].metrics[0]
    cpu_lr = ps_lr[0].metrics[0]
    expected = true_w[0] * 1e6 + true_w[1] * 2e6
    assert abs(cpu_lr - expected) / expected < 1e-3
    assert cpu_lr != cpu_static


def test_lr_model_bucket_readiness_gate():
    """linear.regression.model.* readiness
    (LinearRegressionModelParameters.java:40-75): the fit refuses to mark
    the model trained until the CPU-utilization PERCENT range covers the
    configured number of full bucket_size-wide buckets."""
    from cruise_control_tpu.models.cluster import LinearRegressionCpuModel
    rng = np.random.default_rng(7)
    n = 500
    lbi = rng.uniform(1e6, 5e7, n)
    lbo = rng.uniform(1e6, 5e7, n)
    fbi = rng.uniform(1e5, 1e7, n)
    cpu = (2e-8 * lbi + 1e-8 * lbo + 5e-9 * fbi) * 40   # percent, wide
    assert cpu.max() - cpu.min() > 25.0            # spans >5 5%-buckets
    m = LinearRegressionCpuModel.fit(lbi, lbo, fbi, cpu,
                                     cpu_util_bucket_size=5,
                                     min_num_buckets=5,
                                     samples_per_bucket=10)
    assert m.trained, "wide CPU spread must satisfy 5 full 5%-buckets"

    # a narrow CPU band (all samples inside ~1 bucket) must NOT train
    narrow_scale = 1.0 / (cpu / cpu.mean())
    cpu_narrow = cpu * narrow_scale * 10.0         # constant 10%
    m2 = LinearRegressionCpuModel.fit(lbi, lbo, fbi, cpu_narrow,
                                      cpu_util_bucket_size=5,
                                      min_num_buckets=5,
                                      samples_per_bucket=10)
    assert not m2.trained


def test_windowed_loads_in_model():
    """The model carries [W]-windowed per-replica loads (Load.java:84-118):
    the collapsed vector equals the window AVG, and the MAX-window broker
    load matches a hand-computed value."""
    from cruise_control_tpu.monitor.load_monitor import LoadMonitor, StaticMetadataSource
    from cruise_control_tpu.monitor.sampler import (BrokerMetadata,
                                                    ClusterMetadata,
                                                    MetricSampler,
                                                    PartitionMetadata,
                                                    PartitionMetricSample)
    from cruise_control_tpu.monitor import metricdef as md2
    from cruise_control_tpu.common import resources as res2

    meta = ClusterMetadata(
        brokers=[BrokerMetadata(0, rack="r0", host="h0"),
                 BrokerMetadata(1, rack="r1", host="h1")],
        partitions=[PartitionMetadata("T", 0, leader=0, replicas=(0, 1))],
        generation=1)

    # one partition, 3 windows with NW_IN = 100, 200, 600
    class WindowSampler(MetricSampler):
        def get_samples(self, metadata, start_ms, end_ms):
            w = ((start_ms + end_ms) // 2) // W   # the window the sample lands in
            nw_in = {0: 100.0, 1: 200.0, 2: 600.0}.get(w, 0.0)
            m = np.full(md2.NUM_MODEL_METRICS, np.nan)
            m[md2.ModelMetric.CPU_USAGE] = 10.0
            m[md2.ModelMetric.DISK_USAGE] = 50.0
            m[md2.ModelMetric.LEADER_BYTES_IN] = nw_in
            m[md2.ModelMetric.LEADER_BYTES_OUT] = 40.0
            return [PartitionMetricSample("T", 0, 0, (start_ms + end_ms) // 2,
                                          m)], []

    lm = LoadMonitor(StaticMetadataSource(meta), WindowSampler(),
                     num_windows=3, window_ms=W, now_fn=lambda: 3 * W)
    for w in range(3):
        lm.sample_once(now_ms=w * W + 30_000)
    topo, assign = lm.cluster_model(now_ms=3 * W)
    assert topo.num_windows == 3
    # collapsed load equals window average for the AVG-strategy NW_IN
    lead_r = int(assign.leader_of[0])
    eff = topo.replica_load(np.asarray(
        assign.is_leader(topo.partition_of_replica)))
    assert abs(eff[lead_r, res2.NW_IN] - 300.0) < 1e-3   # avg(100,200,600)
    # max-window broker load: leader broker's NW_IN peak = 600
    is_lead = np.asarray(assign.is_leader(topo.partition_of_replica))
    mx = topo.expected_broker_utilization(np.asarray(assign.broker_of),
                                          is_lead, use_max=True)
    lead_broker = int(np.asarray(assign.broker_of)[lead_r])
    assert abs(mx[lead_broker, res2.NW_IN] - 600.0) < 1e-3
    avg = topo.expected_broker_utilization(np.asarray(assign.broker_of),
                                           is_lead, use_max=False)
    assert abs(avg[lead_broker, res2.NW_IN] - 300.0) < 1e-3


def test_metric_fetcher_manager_partition_assignment():
    """MetricFetcherManager (MetricFetcherManager.java:32-86): partitions
    split round-robin across fetchers, results merged, broker samples
    deduplicated, and a failing fetcher forfeits only its own slice."""
    from cruise_control_tpu.monitor.fetcher import MetricFetcherManager
    from cruise_control_tpu.monitor.load_monitor import LoadMonitor, StaticMetadataSource
    from cruise_control_tpu.monitor.sampler import MetricSampler, SyntheticLoadSampler

    md_src = _metadata()
    sampler = SyntheticLoadSampler(seed=3)
    single = MetricFetcherManager(sampler, num_fetchers=1)
    multi = MetricFetcherManager(sampler, num_fetchers=3)
    # assignment covers every partition exactly once
    slices = multi.assign_partitions(md_src)
    all_parts = [(p.topic, p.partition) for s in slices for p in s.partitions]
    assert sorted(all_parts) == sorted((p.topic, p.partition)
                                       for p in md_src.partitions)
    ps1, bs1 = single.fetch(md_src, 0, W)
    ps3, bs3 = multi.fetch(md_src, 0, W)
    assert len(ps3) == len(ps1)
    assert {b.broker_id for b in bs3} == {b.broker_id for b in bs1}

    class Flaky(MetricSampler):
        """Fails for the slice containing partition 0."""
        def __init__(self, inner):
            self.inner = inner
        def get_samples(self, metadata, start_ms, end_ms):
            if any(p.partition == 0 and p.topic == "T" for p in metadata.partitions):
                raise RuntimeError("boom")
            return self.inner.get_samples(metadata, start_ms, end_ms)

    flaky = MetricFetcherManager(Flaky(sampler), num_fetchers=3)
    psf, _ = flaky.fetch(md_src, 0, W)
    assert 0 < len(psf) < len(ps1)           # one slice lost, others landed
    assert flaky.stats["failed_fetchers"] == 1

    # end-to-end through the monitor
    lm = LoadMonitor(StaticMetadataSource(md_src), sampler, num_windows=3,
                     window_ms=W, num_metric_fetchers=4)
    for w in range(4):
        lm.sample_once(now_ms=w * W + 30_000)
    topo, assign = lm.cluster_model(now_ms=3 * W)
    assert topo.num_partitions == len(md_src.partitions)


def test_pause_during_training_takes_effect_after():
    """A pause issued while TRAIN holds the monitor in TRAINING state must
    not be silently dropped: it applies when training finishes."""
    import threading as _t
    from cruise_control_tpu.monitor.load_monitor import (
        LoadMonitor, MonitorState, StaticMetadataSource)
    from cruise_control_tpu.monitor.sampler import SyntheticLoadSampler
    lm = LoadMonitor(StaticMetadataSource(_metadata()),
                     SyntheticLoadSampler(seed=2), window_ms=W)
    lm._state = MonitorState.RUNNING
    gate = _t.Event()
    orig_fetch = lm._fetchers.fetch

    def slow_fetch(md, s, e):
        gate.wait(5)
        return orig_fetch(md, s, e)

    lm._fetchers.fetch = slow_fetch
    th = _t.Thread(target=lambda: lm.train(0, W))
    th.start()
    for _ in range(100):
        if lm.state == MonitorState.TRAINING:
            break
        time.sleep(0.01)
    lm.pause("maintenance")
    gate.set()
    th.join(timeout=10)
    assert lm.state == MonitorState.PAUSED       # pause survived training
    lm.resume()
    assert lm.state == MonitorState.RUNNING


def test_resume_during_training_cancels_pending_pause():
    """pause → resume while TRAIN is running must leave the monitor RUNNING
    when training finishes (resume clears _pause_after_training)."""
    import threading as _t
    from cruise_control_tpu.monitor.load_monitor import (
        LoadMonitor, MonitorState, StaticMetadataSource)
    from cruise_control_tpu.monitor.sampler import SyntheticLoadSampler
    lm = LoadMonitor(StaticMetadataSource(_metadata()),
                     SyntheticLoadSampler(seed=2), window_ms=W)
    lm._state = MonitorState.RUNNING
    gate = _t.Event()
    orig_fetch = lm._fetchers.fetch

    def slow_fetch(md, s, e):
        gate.wait(5)
        return orig_fetch(md, s, e)

    lm._fetchers.fetch = slow_fetch
    th = _t.Thread(target=lambda: lm.train(0, W))
    th.start()
    for _ in range(100):
        if lm.state == MonitorState.TRAINING:
            break
        time.sleep(0.01)
    lm.pause("maintenance")
    lm.resume("never mind")
    gate.set()
    th.join(timeout=10)
    assert lm.state == MonitorState.RUNNING


def test_resume_during_training_of_previously_paused_monitor():
    """A monitor PAUSED before TRAIN starts, then resumed mid-TRAIN, must be
    RUNNING when training finishes (the resume is not silently lost to the
    captured pre-training state)."""
    import threading as _t
    from cruise_control_tpu.monitor.load_monitor import (
        LoadMonitor, MonitorState, StaticMetadataSource)
    from cruise_control_tpu.monitor.sampler import SyntheticLoadSampler
    lm = LoadMonitor(StaticMetadataSource(_metadata()),
                     SyntheticLoadSampler(seed=2), window_ms=W)
    lm._state = MonitorState.RUNNING
    lm.pause("maintenance")
    assert lm.state == MonitorState.PAUSED
    gate = _t.Event()
    orig_fetch = lm._fetchers.fetch

    def slow_fetch(md, s, e):
        gate.wait(5)
        return orig_fetch(md, s, e)

    lm._fetchers.fetch = slow_fetch
    th = _t.Thread(target=lambda: lm.train(0, W))
    th.start()
    for _ in range(100):
        if lm.state == MonitorState.TRAINING:
            break
        time.sleep(0.01)
    lm.resume("maintenance over")
    gate.set()
    th.join(timeout=10)
    assert lm.state == MonitorState.RUNNING


@pytest.mark.smoke
@pytest.mark.parametrize("include_all_topics", [False, True])
def test_bulk_model_build_matches_builder(monkeypatch, include_all_topics):
    """_build_model_bulk (the vectorized LinkedIn-scale path) must produce
    exactly the same ClusterTopology arrays and Assignment as the builder
    path — dead brokers, offline replicas, unmonitored partitions, mixed
    replication factors, interleaved topics, non-contiguous broker ids.
    The bulk leg enters through the PUBLIC ``_build_model`` dispatch (with
    ``BULK_BUILD_THRESHOLD`` lowered) so the call-site arity is covered —
    round 3 shipped an arity mismatch this test's direct call missed.
    ``include_all_topics=True`` checks zero-load inclusion of unmonitored
    partitions on BOTH paths (LoadMonitor.java:469-541)."""
    import dataclasses as _dc
    import numpy as _np
    from cruise_control_tpu.monitor.aggregator import (
        AggregationResult, Completeness)
    from cruise_control_tpu.monitor import metricdef as _md
    from cruise_control_tpu.monitor.load_monitor import (
        LoadMonitor, StaticMetadataSource)
    from cruise_control_tpu.monitor.sampler import (
        BrokerMetadata, ClusterMetadata, PartitionMetadata,
        SyntheticLoadSampler)

    rng = _np.random.default_rng(11)
    ids = [10, 3, 7, 22, 15, 4]                       # non-contiguous, unsorted
    brokers = [BrokerMetadata(b, rack=f"r{i % 3}", host=f"h{b}",
                              alive=(b != 22)) for i, b in enumerate(ids)]
    parts = []
    for p in range(40):
        topic = f"T{p % 5}"
        rf = 2 + (p % 2)
        reps = tuple(int(x) for x in rng.choice(ids, size=rf, replace=False))
        offline = (reps[1],) if p % 11 == 0 else ()
        parts.append(PartitionMetadata(topic, p // 5, leader=reps[0],
                                       replicas=reps,
                                       offline_replicas=offline))
    metadata = ClusterMetadata(brokers=brokers, partitions=parts, generation=1)
    W = 3
    # leave two partitions unmonitored
    entities = [(pm.topic, pm.partition) for pm in parts[:-2]]
    values = rng.exponential(40.0, (len(entities), W, _md.NUM_MODEL_METRICS))
    result = AggregationResult(
        entities=entities, values=values,
        window_times=_np.arange(W, dtype=_np.int64) * 60_000,
        extrapolations=_np.zeros((len(entities), W), _np.int8),
        completeness=Completeness(_np.ones(W, _np.float32), 1.0, 1, W,
                                  len(entities)),
        generation=1)
    lm = LoadMonitor(StaticMetadataSource(metadata), SyntheticLoadSampler())
    topo_a, assign_a = lm._build_model(             # builder (small path)
        metadata, result, include_all_topics=include_all_topics)
    monkeypatch.setattr(LoadMonitor, "BULK_BUILD_THRESHOLD", 1)
    topo_b, assign_b = lm._build_model(             # bulk, via dispatch
        metadata, result, include_all_topics=include_all_topics)

    for f in _dc.fields(topo_a):
        va, vb = getattr(topo_a, f.name), getattr(topo_b, f.name)
        if va is None or isinstance(va, tuple):
            assert va == vb or (va is None and vb is None), f.name
        else:
            _np.testing.assert_allclose(
                _np.asarray(va, dtype=_np.float64),
                _np.asarray(vb, dtype=_np.float64),
                rtol=1e-6, atol=1e-6, err_msg=f.name)
    _np.testing.assert_array_equal(_np.asarray(assign_a.broker_of),
                                   _np.asarray(assign_b.broker_of))
    _np.testing.assert_array_equal(_np.asarray(assign_a.leader_of),
                                   _np.asarray(assign_b.leader_of))


@pytest.mark.parametrize("overlap_free_entities", [False, True])
def test_bulk_model_build_all_unmonitored_matches_builder(
        monkeypatch, overlap_free_entities):
    """Edge parity: include_all_topics=True with ZERO monitored partitions —
    the builder emits n_windows == 0 (windows fields None); the bulk path
    must match, not fabricate zero-filled window arrays. Covers both an
    empty entity list and a non-empty one overlapping NO kept partition
    (e.g. the monitored topics were deleted from metadata between sampling
    and model build)."""
    import dataclasses as _dc
    from cruise_control_tpu.monitor.aggregator import (
        AggregationResult, Completeness)
    brokers = [BrokerMetadata(b, rack=f"r{b % 2}", host=f"h{b}", alive=True)
               for b in range(4)]
    parts = [PartitionMetadata("T", p, leader=p % 4,
                               replicas=(p % 4, (p + 1) % 4))
             for p in range(8)]
    metadata = ClusterMetadata(brokers=brokers, partitions=parts, generation=1)
    nW = 2
    entities = ([("deleted-topic", p) for p in range(3)]
                if overlap_free_entities else [])
    result = AggregationResult(
        entities=entities,
        values=np.ones((len(entities), nW, md.NUM_MODEL_METRICS)),
        window_times=np.arange(nW, dtype=np.int64) * 60_000,
        extrapolations=np.zeros((len(entities), nW), np.int8),
        completeness=Completeness(np.ones(nW, np.float32), 1.0, 1, nW,
                                  len(entities)),
        generation=1)
    lm = LoadMonitor(StaticMetadataSource(metadata), SyntheticLoadSampler())
    topo_a, assign_a = lm._build_model(metadata, result,
                                       include_all_topics=True)
    monkeypatch.setattr(LoadMonitor, "BULK_BUILD_THRESHOLD", 1)
    topo_b, assign_b = lm._build_model(metadata, result,
                                       include_all_topics=True)
    assert topo_a.num_windows == topo_b.num_windows == 0
    assert topo_b.replica_base_load_windows is None
    assert topo_b.leader_extra_windows is None
    import dataclasses
    for f in dataclasses.fields(topo_a):
        va, vb = getattr(topo_a, f.name), getattr(topo_b, f.name)
        if va is None or isinstance(va, tuple):
            assert va == vb or (va is None and vb is None), f.name
        else:
            np.testing.assert_allclose(np.asarray(va, np.float64),
                                       np.asarray(vb, np.float64),
                                       err_msg=f.name)
    np.testing.assert_array_equal(np.asarray(assign_a.broker_of),
                                  np.asarray(assign_b.broker_of))


@pytest.mark.smoke
@pytest.mark.parametrize("include_all_topics", [False, True])
def test_build_model_dispatches_bulk_at_real_threshold(include_all_topics):
    """At >= BULK_BUILD_THRESHOLD partitions the PUBLIC ``_build_model``
    dispatch must reach the bulk path with the real signature — the exact
    call the driver bench makes (round 3's bench crashed here on an arity
    mismatch no test covered). Also checks include_all_topics semantics at
    scale: unmonitored partitions kept with zero load, or dropped."""
    rng = np.random.default_rng(5)
    n_brokers, n_parts = 40, LoadMonitor.BULK_BUILD_THRESHOLD + 500
    ids = list(range(n_brokers))
    brokers = [BrokerMetadata(b, rack=f"r{b % 4}", host=f"h{b}", alive=True)
               for b in ids]
    parts = []
    for p in range(n_parts):
        reps = tuple(int(x) for x in rng.choice(ids, size=3, replace=False))
        parts.append(PartitionMetadata(f"T{p % 200}", p // 200,
                                       leader=reps[0], replicas=reps))
    metadata = ClusterMetadata(brokers=brokers, partitions=parts, generation=1)
    n_unmonitored = 750
    from cruise_control_tpu.monitor.aggregator import (
        AggregationResult, Completeness)
    entities = [(pm.topic, pm.partition) for pm in parts[:-n_unmonitored]]
    nW = 2
    values = rng.exponential(
        30.0, (len(entities), nW, md.NUM_MODEL_METRICS)).astype(np.float32)
    result = AggregationResult(
        entities=entities, values=values,
        window_times=np.arange(nW, dtype=np.int64) * 60_000,
        extrapolations=np.zeros((len(entities), nW), np.int8),
        completeness=Completeness(np.ones(nW, np.float32), 1.0, 1, nW,
                                  len(entities)),
        generation=1)
    lm = LoadMonitor(StaticMetadataSource(metadata), SyntheticLoadSampler())
    topo, assign = lm._build_model(metadata, result,
                                   include_all_topics=include_all_topics)
    expected = n_parts if include_all_topics else n_parts - n_unmonitored
    assert len(topo.rf_of_partition) == expected
    if include_all_topics:
        # unmonitored partitions are structurally present with zero load
        per_part_load = np.asarray(topo.leader_extra)
        monitored_ents = set(entities)
        names = topo.topic_names
        unmon = [i for i in range(expected)
                 if (names[int(topo.topic_of_partition[i])],
                     int(topo.partition_index[i])) not in monitored_ents]
        assert len(unmon) == n_unmonitored
        assert float(np.abs(per_part_load[unmon]).max()) == 0.0
        base = np.asarray(topo.replica_base_load)
        pid_of_replica = np.asarray(topo.partition_of_replica)
        unmon_mask = np.isin(pid_of_replica, np.asarray(unmon))
        assert float(np.abs(base[unmon_mask]).max()) == 0.0
