"""Sequential reference baseline + comparator parity oracle tests.

Covers SURVEY §4 tier 3 ("goal-parity tests asserting the JAX penalty ranks
states identically to each Java ``ClusterModelStatsComparator``") via the
round-5 sequential port (``analyzer/sequential.py``):

1. The mutable ``SeqModel``'s incremental aggregates stay exact under random
   action fuzz (the ``sanityCheck()`` discipline, ``ClusterModel.java:1081``).
2. The sequential engine itself never regresses any reference comparator —
   the contract ``AbstractGoal.java:97`` enforces with an exception.
3. The TPU engine's OUTPUT, ranked by the reference's own comparators, is
   never a regression either: the JAX objective cannot prefer a state any
   reference comparator rejects.
4. Penalty↔comparator monotone agreement: across random states, each soft
   goal's JAX cost moves WITH the comparator's statistic (a penalty that
   monotonically disagreed with the reference's preference order — the
   failure class VERDICT r4 missing #2 names — shows up as non-positive
   correlation here).
5. Hard-goal violation parity between the JAX penalties and the sequential
   model's definitions on random states.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.analyzer import objective as OBJ
from cruise_control_tpu.analyzer import optimizer as OPT
from cruise_control_tpu.analyzer import sequential as SEQ
from cruise_control_tpu.common import resources as res
from cruise_control_tpu.models import fixtures
from cruise_control_tpu.models.cluster import Assignment


def _host(a):
    return np.asarray(jax.device_get(a))


def _fixture(seed=7, brokers=20, replicas=400, topics=12, racks=4):
    topo, assign = fixtures.synthetic_cluster(
        num_brokers=brokers, num_replicas=replicas, num_racks=racks,
        num_topics=topics, seed=seed)
    return topo, _host(assign.broker_of), _host(assign.leader_of)


def _random_actions(m: SEQ.SeqModel, rng, n: int):
    """Apply n random LEGAL actions (moves + leadership) to the model."""
    for _ in range(n):
        if rng.random() < 0.3:
            p = int(rng.integers(m.P))
            reps = [r for r in m.reps_of_p[p] if r >= 0]
            m.relocate_leadership(p, int(rng.choice(reps)))
        else:
            r = int(rng.integers(m.R))
            p = int(m.part_of[r])
            dests = [b for b in range(m.B)
                     if (b, p) not in m.rep_at and m.alive[b]]
            if dests:
                m.relocate_replica(r, int(rng.choice(dests)))


def test_seq_model_incremental_aggregates_match_scratch():
    """Fuzz the mutation ops; every incremental aggregate must equal a
    from-scratch recomputation (the reference's sanityCheck discipline)."""
    topo, bo, lo = _fixture()
    m = SEQ.SeqModel(topo, bo, lo)
    rng = np.random.default_rng(5)
    _random_actions(m, rng, 300)

    fresh = SEQ.SeqModel(topo, m.broker_of.copy(), m.leader_of.copy())
    # carry over immigrant tracking (fresh model treats current as original)
    np.testing.assert_allclose(m.broker_load, fresh.broker_load,
                               rtol=1e-9, atol=1e-6)
    np.testing.assert_allclose(m.host_load, fresh.host_load,
                               rtol=1e-9, atol=1e-6)
    np.testing.assert_allclose(m.lead_load, fresh.lead_load,
                               rtol=1e-9, atol=1e-6)
    np.testing.assert_allclose(m.pot_nw_out, fresh.pot_nw_out,
                               rtol=1e-9, atol=1e-6)
    np.testing.assert_array_equal(m.replica_count, fresh.replica_count)
    np.testing.assert_array_equal(m.leader_count, fresh.leader_count)
    for b in range(m.B):
        assert m.topic_count[b] == fresh.topic_count[b]
        assert m.replicas_on[b] == fresh.replicas_on[b]


def test_sequential_engine_never_regresses_any_comparator():
    """AbstractGoal.java:97: after each goal's optimization the goal's own
    comparator must not rank the result worse than before."""
    topo, bo, lo = _fixture(seed=11)
    r = SEQ.optimize_sequential(topo, bo, lo)
    for rep in r.goal_reports:
        assert rep.comparator_vs_before >= 0, (
            rep.name, rep.comparator_vs_before)
    # final placement is valid: no partition has two replicas on one broker
    m = SEQ.SeqModel(topo, r.broker_of, r.leader_of)
    for p in range(m.P):
        brokers = m.partition_brokers(p)
        assert len(brokers) == len(set(brokers))


def test_tpu_engine_output_never_regresses_reference_comparators():
    """The keystone parity assertion: run the JAX engine, then rank its
    before/after states with the REFERENCE's comparator semantics
    (goals/Goal.java:128 implementations in sequential.compare_stats).
    The JAX objective must never prefer a state any reference comparator
    ranks as a regression."""
    topo, bo, lo = _fixture(seed=13)
    assign = Assignment(broker_of=jnp.asarray(bo, jnp.int32),
                        leader_of=jnp.asarray(lo, jnp.int32))
    result = OPT.optimize(topo, assign, seed=13)
    fa = result.final_assignment
    constraint = res.DEFAULT_BALANCING_CONSTRAINT
    s_before = SEQ.compute_seq_stats(SEQ.SeqModel(topo, bo, lo), constraint)
    s_after = SEQ.compute_seq_stats(
        SEQ.SeqModel(topo, _host(fa.broker_of), _host(fa.leader_of)),
        constraint)
    for g in G.DEFAULT_GOALS:
        assert SEQ.compare_stats(g, s_after, s_before, constraint) >= 0, g


def test_soft_goal_penalties_track_comparator_statistics():
    """Monotone agreement between the JAX per-goal costs and the statistic
    each reference comparator ranks by, across random states of one topology.
    Pearson correlation must be strongly positive for every soft goal — a
    penalty term that monotonically disagreed with the reference's
    preference order would correlate negatively."""
    topo, bo, lo = _fixture(seed=17, brokers=16, replicas=360, topics=10)
    goal_names = tuple(G.DEFAULT_GOALS)
    # Tight bands so every soft penalty actually engages across the random
    # states — with the defaults (e.g. topic balance 3.00) most costs are
    # identically zero here and a correlation over a flat series is noise,
    # not evidence (found in round 5: the default-band run "failed" on one
    # vacuous point).
    constraint = res.BalancingConstraint(
        resource_balance_percentage=(1.02, 1.02, 1.02, 1.02),
        replica_balance_percentage=1.02,
        leader_replica_balance_percentage=1.02,
        topic_replica_balance_percentage=1.05)
    (constraint, opts, dt, num_topics, sparse_topic, init_broker, _agg,
     agg0, th, weights) = OPT._setup_model(
        topo, Assignment(jnp.asarray(bo, jnp.int32),
                         jnp.asarray(lo, jnp.int32)),
        goal_names, constraint, None, None)

    rng = np.random.default_rng(23)
    costs, stats = [], []
    for k in range(14):
        m = SEQ.SeqModel(topo, bo, lo)
        _random_actions(m, rng, 40 * k)
        a = Assignment(jnp.asarray(m.broker_of, jnp.int32),
                       jnp.asarray(m.leader_of, jnp.int32))
        ev = OBJ.evaluate_objective(dt, a, th, weights, goal_names,
                                    num_topics, init_broker, _agg(a),
                                    sparse_topic=sparse_topic)
        costs.append(np.asarray(ev.penalties.cost, np.float64))
        stats.append(SEQ.compute_seq_stats(m, constraint))

    costs = np.stack(costs)          # [K, G+1]
    gi = {g: i for i, g in enumerate(goal_names)}

    def corr(xs, ys):
        """Spearman rank correlation — the claim under test is MONOTONE
        agreement (same preference order), not linearity: the band costs
        are zero-floored and ceil-quantized, so Pearson understates
        agreement even when the orderings match."""
        xs, ys = np.asarray(xs, np.float64), np.asarray(ys, np.float64)
        if xs.std() == 0 or ys.std() == 0:
            return 1.0               # both flat — vacuous agreement
        rx = np.argsort(np.argsort(xs)).astype(np.float64)
        ry = np.argsort(np.argsort(ys)).astype(np.float64)
        return float(np.corrcoef(rx, ry)[0, 1])

    pairs = {
        "ReplicaDistributionGoal": [s.replica_stdev for s in stats],
        "LeaderReplicaDistributionGoal": [s.leader_stdev for s in stats],
        "DiskUsageDistributionGoal":
            [s.stdev_util[res.DISK] for s in stats],
        "NetworkInboundUsageDistributionGoal":
            [s.stdev_util[res.NW_IN] for s in stats],
        "NetworkOutboundUsageDistributionGoal":
            [s.stdev_util[res.NW_OUT] for s in stats],
        "CpuUsageDistributionGoal":
            [s.stdev_util[res.CPU] for s in stats],
        "PotentialNwOutGoal":
            [-s.num_brokers_under_pot_nw_out for s in stats],
    }
    for g, series in pairs.items():
        c = corr(costs[:, gi[g]], series)
        assert c > 0.5, (g, c)
    # TopicReplicaDistributionGoal: the reference's comparator statistic
    # (mean over topics of per-topic stdev) and the goal's own band
    # criterion order random states only weakly — BY DESIGN in the
    # reference (the comparator is a regression guard, not the goal's
    # objective; TopicReplicaDistrGoalStatsComparator vs the per-topic
    # balance limits of TopicReplicaDistributionGoal.java:106-133). The
    # meaningful parity is against the band criterion itself: the JAX
    # violation count must EXACTLY equal a host-side recount of
    # out-of-band (alive broker, topic) cells at the same thresholds.
    t_upper = np.asarray(jax.device_get(th.topic_upper))
    t_lower = np.asarray(jax.device_get(th.topic_lower))
    rng = np.random.default_rng(23)
    for k in range(14):
        m = SEQ.SeqModel(topo, bo, lo)
        _random_actions(m, rng, 40 * k)
        a = Assignment(jnp.asarray(m.broker_of, jnp.int32),
                       jnp.asarray(m.leader_of, jnp.int32))
        ev = OBJ.evaluate_objective(dt, a, th, weights, goal_names,
                                    num_topics, init_broker, _agg(a),
                                    sparse_topic=sparse_topic)
        viol = float(np.asarray(
            ev.penalties.violations)[gi["TopicReplicaDistributionGoal"]])
        n_cells = 0
        for b in range(m.B):
            if not m.alive[b]:
                continue
            for t in range(m.T):
                c = m.topic_count[b].get(t, 0)
                if c > t_upper[t] or c < t_lower[t]:
                    n_cells += 1
        assert viol == n_cells, (k, viol, n_cells)


def test_hard_goal_violation_parity_on_random_states():
    """JAX hard-goal violation indicators match the sequential model's
    reference definitions exactly on random states."""
    topo, bo, lo = _fixture(seed=29, brokers=12, replicas=240, topics=8)
    goal_names = tuple(G.DEFAULT_GOALS)
    constraint = res.BalancingConstraint(max_replicas_per_broker=30)
    (constraint, opts, dt, num_topics, sparse_topic, init_broker, _agg,
     agg0, th, weights) = OPT._setup_model(
        topo, Assignment(jnp.asarray(bo, jnp.int32),
                         jnp.asarray(lo, jnp.int32)),
        goal_names, constraint, None, None)
    gi = {g: i for i, g in enumerate(goal_names)}
    rng = np.random.default_rng(31)
    for k in range(6):
        m = SEQ.SeqModel(topo, bo, lo)
        _random_actions(m, rng, 60 * k)
        a = Assignment(jnp.asarray(m.broker_of, jnp.int32),
                       jnp.asarray(m.leader_of, jnp.int32))
        ev = OBJ.evaluate_objective(dt, a, th, weights, goal_names,
                                    num_topics, init_broker, _agg(a),
                                    sparse_topic=sparse_topic)
        viol = np.asarray(ev.penalties.violations, np.float64)

        # rack awareness: any replica sharing a rack with a same-partition
        # peer (RackAwareGoal.java:298-316)
        rack_viol = 0
        for p in range(m.P):
            racks = [int(m.rack_of_b[b]) for b in m.partition_brokers(p)]
            rack_viol += len(racks) - len(set(racks))
        assert (viol[gi["RackAwareGoal"]] > 0) == (rack_viol > 0), k

        # replica capacity: brokers above max.replicas.per.broker
        over = int((m.replica_count
                    > constraint.max_replicas_per_broker).sum())
        assert (viol[gi["ReplicaCapacityGoal"]] > 0) == (over > 0), k

        # capacity goals: broker/host scope over capacity*threshold
        for g, rr in SEQ._CAPACITY_RESOURCE.items():
            thresh = constraint.capacity_threshold[rr]
            n_over = 0
            for b in range(m.B):
                if SEQ.res.IS_BROKER_RESOURCE[rr] and (
                        m.broker_load[b, rr] > m.cap[b, rr] * thresh):
                    n_over += 1
                    continue
                if SEQ.res.IS_HOST_RESOURCE[rr]:
                    h = m.host_of_b[b]
                    if m.host_load[h, rr] > m.host_cap[h, rr] * thresh:
                        n_over += 1
            assert (viol[gi[g]] > 0) == (n_over > 0), (g, k)


def test_sequential_vs_tpu_engine_quality_small():
    """Both engines on DeterministicCluster.smallClusterModel: the TPU
    engine's final violation count must be equal-or-better than the
    sequential baseline's (the north star's quality half), evaluated by
    ONE objective (the repo's)."""
    topo, assign = fixtures.small_cluster_model()
    bo, lo = _host(assign.broker_of), _host(assign.leader_of)
    seq = SEQ.optimize_sequential(topo, bo, lo)
    goal_names = tuple(G.DEFAULT_GOALS)
    (constraint, opts, dt, num_topics, sparse_topic, init_broker, _agg,
     agg0, th, weights) = OPT._setup_model(topo, assign, goal_names,
                                           None, None, None)

    def viols(a):
        ev = OBJ.evaluate_objective(dt, a, th, weights, goal_names,
                                    num_topics, init_broker, _agg(a),
                                    sparse_topic=sparse_topic)
        return float(np.asarray(ev.penalties.violations).sum())

    a_seq = Assignment(jnp.asarray(seq.broker_of, jnp.int32),
                       jnp.asarray(seq.leader_of, jnp.int32))
    r_tpu = OPT.optimize(topo, assign, seed=3)
    assert viols(r_tpu.final_assignment) <= viols(a_seq)


# -- GoalUtils.eligibleBrokers parity (ADVICE round-5 drift fix) -----------

def _seq_model(fix):
    topo, assign = fix()
    return topo, SEQ.SeqModel(topo, _host(assign.broker_of),
                              _host(assign.leader_of))


def test_eligible_brokers_requested_destinations_replace_exclusions():
    """GoalUtils.java:100-104: when destination brokers are REQUESTED and
    the action is not leadership movement, the requested-set intersection
    REPLACES the exclusion filters (the caller explicitly picked the
    destinations), and the early return also skips the new-broker
    invariant (GoalUtils.java:130-132)."""
    _, m = _seq_model(fixtures.small_cluster_model)
    r = 0
    goal = SEQ.SeqGoal(None, SEQ.SeqOptions(
        excluded_brokers_for_replica_move=frozenset({1}),
        excluded_brokers_for_leadership=frozenset({1}),
        requested_destination_broker_ids=frozenset({1, 2})))
    # broker 1 is excluded-for-move AND requested: requested wins for MOVE
    assert goal._eligible_brokers(m, r, [0, 1, 2], SEQ.MOVE) == [1, 2]
    # LEAD keeps the leadership-exclusion filter (requested destinations
    # apply to replica placement, not leadership)
    assert goal._eligible_brokers(m, r, [0, 1, 2], SEQ.LEAD) == [0, 2]


def test_eligible_brokers_exclusion_applies_to_offline_replicas():
    """The reference exempts offline replicas from the exclusion filters
    only in eligibleReplicasForSwap (GoalUtils.java:207-212); the
    per-action eligible-brokers path applies them unconditionally — an
    offline replica must NOT slip onto an excluded broker."""
    _, m = _seq_model(fixtures.dead_broker)
    off = [r for r in range(m.R) if m.offline[r]]
    assert off, "dead_broker fixture must produce offline replicas"
    r = off[0]
    goal = SEQ.SeqGoal(None, SEQ.SeqOptions(
        excluded_brokers_for_replica_move=frozenset({3})))
    out = goal._eligible_brokers(m, r, [1, 2, 3, 4], SEQ.MOVE)
    assert 3 not in out
    assert out == [1, 2, 4]


def test_eligible_brokers_new_broker_invariant_without_requests():
    """Without requested destinations the new-broker invariant holds: on a
    cluster with NEW brokers, eligible MOVE destinations shrink to the new
    brokers plus the replica's original broker (GoalUtils.java:130-140)."""
    import dataclasses as _dc
    topo, assign = fixtures.small_cluster_model()
    new = np.zeros(topo.num_brokers, bool)
    new[2] = True
    topo2 = _dc.replace(topo, broker_new=new)
    m = SEQ.SeqModel(topo2, _host(assign.broker_of), _host(assign.leader_of))
    goal = SEQ.SeqGoal(None, SEQ.SeqOptions())
    r = 0
    orig = int(m.orig_broker[r])
    out = goal._eligible_brokers(m, r, list(range(m.B)), SEQ.MOVE)
    assert set(out) <= {2, orig}
