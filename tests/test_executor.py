"""Executor tests, modeled on the reference's ExecutorTest (which runs real
reassignments against embedded brokers — here against FakeClusterAdapter):
full execution lifecycle, strategies ordering, concurrency bounds, stop
semantics, dead-broker task death, throttling.
"""

import threading
import time

import pytest

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.executor import (
    Executor,
    ExecutorConfig,
    ExecutorNotifier,
    ExecutorState,
    FakeClusterAdapter,
)
from cruise_control_tpu.executor.tasks import (
    ExecutionTask,
    ExecutionTaskPlanner,
    PostponeUrpReplicaMovementStrategy,
    PrioritizeLargeReplicaMovementStrategy,
    PrioritizeSmallReplicaMovementStrategy,
    TaskState,
    TaskType,
)


def _proposal(topic, part, old, new, size=10.0):
    return ExecutionProposal(topic=topic, partition=part, old_leader=old[0],
                             old_replicas=tuple(old), new_replicas=tuple(new),
                             data_size=size)


def _adapter_for(proposals, latency=1):
    return FakeClusterAdapter(
        {p.topic_partition: p.old_replicas for p in proposals},
        latency_polls=latency)


def test_execute_replica_and_leadership_moves():
    props = [
        _proposal("t", 0, [0, 1], [2, 1]),        # replica move
        _proposal("t", 1, [1, 0], [0, 1]),        # leadership-only change
    ]
    adapter = _adapter_for(props, latency=2)
    ex = Executor(adapter, ExecutorConfig(execution_progress_check_interval_ms=1))
    summary = ex.execute_proposals(props)
    assert adapter.replicas["t-0"] == (2, 1)
    assert adapter.leaders["t-1"] == 0
    counts = summary["taskCounts"]
    assert counts["INTER_BROKER_REPLICA_ACTION"]["COMPLETED"] == 1
    # t-0 changes leader (0→2) as part of the move AND t-1 is leadership-only
    assert counts["LEADER_ACTION"]["COMPLETED"] == 2
    assert not summary["stopped"]
    assert ex.state == ExecutorState.NO_TASK_IN_PROGRESS


def test_concurrency_bound_per_broker():
    # 6 moves all involving broker 0: with concurrency 2, batches of <=2
    props = [_proposal("t", i, [0, 1], [2 + (i % 3), 1]) for i in range(6)]
    adapter = _adapter_for(props)
    planner = ExecutionTaskPlanner()
    planner.add_proposals(props)
    batch = planner.next_replica_batch(2, {})
    involved0 = [t for t in batch if 0 in t.brokers_involved()]
    assert len(involved0) <= 2


def test_strategy_ordering():
    small = _proposal("t", 0, [0], [1], size=1.0)
    big = _proposal("t", 1, [0], [2], size=100.0)
    planner = ExecutionTaskPlanner(PrioritizeLargeReplicaMovementStrategy())
    planner.add_proposals([small, big])
    assert planner.replica_tasks[0].proposal.data_size == 100.0
    planner = ExecutionTaskPlanner(PrioritizeSmallReplicaMovementStrategy())
    planner.add_proposals([small, big])
    assert planner.replica_tasks[0].proposal.data_size == 1.0
    # chained: postpone URP first, then size
    urp = {"t-1"}
    chained = PostponeUrpReplicaMovementStrategy().chain(
        PrioritizeLargeReplicaMovementStrategy())
    planner = ExecutionTaskPlanner(chained)
    planner.add_proposals([small, big], urp=urp)
    assert planner.replica_tasks[0].proposal.topic_partition == "t-0"


def test_task_state_machine():
    t = ExecutionTask(0, _proposal("t", 0, [0], [1]),
                      TaskType.INTER_BROKER_REPLICA_ACTION)
    with pytest.raises(ValueError):
        t.transition(TaskState.COMPLETED)      # PENDING -> COMPLETED illegal
    t.transition(TaskState.IN_PROGRESS, 1)
    t.transition(TaskState.ABORTING, 2)
    t.transition(TaskState.ABORTED, 3)
    assert t.done
    with pytest.raises(ValueError):
        t.transition(TaskState.IN_PROGRESS)


def test_dead_broker_kills_task():
    props = [_proposal("t", 0, [0, 1], [2, 1])]
    adapter = _adapter_for(props, latency=10_000)   # never completes
    adapter.kill_broker(2)
    ex = Executor(adapter, ExecutorConfig(execution_progress_check_interval_ms=1))
    summary = ex.execute_proposals(props)
    assert summary["taskCounts"]["INTER_BROKER_REPLICA_ACTION"]["DEAD"] == 1


def test_stop_execution_aborts_pending():
    props = [_proposal("t", i, [0, 1], [2, 1]) for i in range(4)]
    adapter = _adapter_for(props, latency=50)
    ex = Executor(adapter, ExecutorConfig(
        execution_progress_check_interval_ms=5,
        num_concurrent_partition_movements_per_broker=1))
    done = {}

    def run():
        done["summary"] = ex.execute_proposals(props)

    th = threading.Thread(target=run)
    th.start()
    time.sleep(0.05)
    ex.stop_execution()
    th.join(timeout=30)
    assert done["summary"]["stopped"]
    counts = done["summary"]["taskCounts"]["INTER_BROKER_REPLICA_ACTION"]
    assert counts.get("ABORTED", 0) + counts.get("COMPLETED", 0) >= 1
    assert counts.get("PENDING", 0) >= 1   # later tasks never started


def test_replication_throttle_set_and_cleared():
    """ReplicationThrottleHelper.java:29-79 semantics: participating brokers
    get the rate, moved topics get leader (old replicas) / follower (added
    replicas) throttled-replica lists; all cleared after the execution."""
    props = [_proposal("t", 0, [0, 1], [2, 1])]
    adapter = _adapter_for(props)
    seen = {"rates": [], "topics": {}}

    class SpyAdapter(FakeClusterAdapter):
        def set_broker_throttle_rate(self, broker_ids, rate):
            seen["rates"].append((tuple(broker_ids), rate))
            super().set_broker_throttle_rate(broker_ids, rate)

        def set_topic_throttled_replicas(self, topic, leaders, followers):
            seen["topics"][topic] = (tuple(leaders), tuple(followers))
            super().set_topic_throttled_replicas(topic, leaders, followers)

    adapter = SpyAdapter({p.topic_partition: p.old_replicas for p in props})
    ex = Executor(adapter, ExecutorConfig(execution_progress_check_interval_ms=1))
    ex.execute_proposals(props, replication_throttle=12345)
    assert seen["rates"] == [((0, 1, 2), 12345)]
    # leader entries = old replicas {0,1}; follower entries = added {2}
    assert seen["topics"]["t"] == (("0:0", "0:1"), ("0:2",))
    assert adapter.broker_throttle_rates == {}       # cleared after execution
    assert adapter.topic_throttled_replicas == {}


def test_replica_move_with_leader_action_gets_leader_task():
    """A proposal that both moves replicas AND changes leadership must get a
    LEADER_ACTION task (ExecutionTaskPlanner.java:250-258): reassignment
    alone does not transfer leadership while the old leader stays in the
    replica set."""
    props = [_proposal("t", 0, [0, 1], [1, 2])]   # 0->2 move, leader 0->1
    adapter = _adapter_for(props)
    ex = Executor(adapter, ExecutorConfig(execution_progress_check_interval_ms=1))
    summary = ex.execute_proposals(props)
    counts = summary["taskCounts"]
    assert counts["INTER_BROKER_REPLICA_ACTION"]["COMPLETED"] == 1
    assert counts["LEADER_ACTION"]["COMPLETED"] == 1
    assert adapter.replicas["t-0"] == (1, 2)
    assert adapter.leaders["t-0"] == 1


def test_forced_stop_drops_in_flight_tasks():
    props = [_proposal("t", i, [0, 1], [2, 1]) for i in range(4)]
    adapter = _adapter_for(props, latency=10_000)   # effectively never finish
    ex = Executor(adapter, ExecutorConfig(
        execution_progress_check_interval_ms=5,
        num_concurrent_partition_movements_per_broker=1))
    done = {}

    def run():
        done["summary"] = ex.execute_proposals(props)

    th = threading.Thread(target=run)
    th.start()
    time.sleep(0.05)
    ex.stop_execution(forced=True)
    th.join(timeout=30)
    assert done["summary"]["stopped"] and done["summary"]["forcedStop"]
    counts = done["summary"]["taskCounts"]["INTER_BROKER_REPLICA_ACTION"]
    assert counts.get("DEAD", 0) >= 1               # in-flight dropped, not drained
    assert counts.get("PENDING", 0) >= 1


def test_round_exhaustion_marks_tasks_dead_and_times_out():
    props = [_proposal("t", 0, [0, 1], [2, 1])]
    adapter = _adapter_for(props, latency=10_000)
    ex = Executor(adapter, ExecutorConfig(
        execution_progress_check_interval_ms=1,
        max_execution_progress_check_rounds=3,
        leader_movement_timeout_ms=3))
    summary = ex.execute_proposals(props)
    assert summary["timedOut"]
    counts = summary["taskCounts"]["INTER_BROKER_REPLICA_ACTION"]
    assert counts.get("DEAD", 0) == 1
    assert counts.get("IN_PROGRESS", 0) == 0        # nothing left dangling


def test_intra_broker_phase_runs_inside_execution():
    class Move:
        def __init__(self):
            self.topic, self.partition, self.broker_id = "t", 0, 0
            self.to_logdir = "/d2"

    props = [_proposal("t", 0, [0, 1], [2, 1])]
    adapter = _adapter_for(props)
    ex = Executor(adapter, ExecutorConfig(execution_progress_check_interval_ms=1))
    summary = ex.execute_proposals(props, logdir_moves=[Move()])
    assert summary["intraBrokerMoves"] == 1
    assert adapter.logdir_by_tp_broker[("t-0", 0)] == "/d2"


def test_notifier_called():
    calls = []

    class N(ExecutorNotifier):
        def on_execution_finished(self, summary):
            calls.append("finished")

    props = [_proposal("t", 0, [0, 1], [2, 1])]
    ex = Executor(_adapter_for(props),
                  ExecutorConfig(execution_progress_check_interval_ms=1),
                  notifier=N())
    ex.execute_proposals(props)
    assert calls == ["finished"]


def test_rejects_concurrent_executions():
    props = [_proposal("t", 0, [0, 1], [2, 1]) for _ in range(1)]
    adapter = _adapter_for(props, latency=100)
    ex = Executor(adapter, ExecutorConfig(execution_progress_check_interval_ms=5))
    th = threading.Thread(target=lambda: ex.execute_proposals(props))
    th.start()
    time.sleep(0.03)
    with pytest.raises(RuntimeError):
        ex.execute_proposals(props)
    ex.stop_execution()
    th.join(timeout=30)


def test_unknown_strategy_rejects_without_wedging_executor():
    """An unknown replica_movement_strategies name (reachable straight from
    REST) must reject the request BEFORE any state transition — previously it
    raised between STARTING_EXECUTION and the try/finally, permanently
    wedging the executor with 'An execution is already in progress'."""
    props = [_proposal("t", 0, [0, 1], [2, 1])]
    adapter = _adapter_for(props)
    ex = Executor(adapter, ExecutorConfig(execution_progress_check_interval_ms=1))
    with pytest.raises(ValueError, match="unknown replica movement strategy"):
        ex.execute_proposals(props, strategy_names=["NoSuchStrategy"])
    assert ex.state == ExecutorState.NO_TASK_IN_PROGRESS
    summary = ex.execute_proposals(props)      # executor still usable
    assert summary["taskCounts"]["INTER_BROKER_REPLICA_ACTION"]["COMPLETED"] == 1


def test_graceful_stop_cancels_reassignments_at_adapter():
    """Graceful stop actively cancels the in-flight reassignment at the
    ADAPTER (Executor.java abort + ExecutorUtils.scala:22-34 /
    KIP-455 cancellation) — not just task-state bookkeeping: the adapter's
    pending moves are withdrawn and the partitions keep their old replicas.
    Forced stop, by contrast, drops tasks without any adapter-side cancel."""
    cancelled = []

    class SpyAdapter(FakeClusterAdapter):
        def cancel_reassignments(self, tasks):
            cancelled.extend(t.proposal.topic_partition for t in tasks)
            super().cancel_reassignments(tasks)

    props = [_proposal("t", i, [0, 1], [2, 1]) for i in range(4)]
    adapter = SpyAdapter({p.topic_partition: p.old_replicas for p in props},
                         latency_polls=10_000)      # never completes on its own
    ex = Executor(adapter, ExecutorConfig(
        execution_progress_check_interval_ms=5,
        num_concurrent_partition_movements_per_broker=4))
    done = {}
    th = threading.Thread(
        target=lambda: done.update(summary=ex.execute_proposals(props)))
    th.start()
    time.sleep(0.05)
    ex.stop_execution(forced=False)
    th.join(timeout=30)
    assert done["summary"]["stopped"] and not done["summary"]["forcedStop"]
    counts = done["summary"]["taskCounts"]["INTER_BROKER_REPLICA_ACTION"]
    assert counts.get("ABORTED", 0) >= 1
    assert len(cancelled) >= 1                      # adapter-side cancel observed
    for tp in cancelled:
        assert tp not in adapter.in_progress_reassignments()
        assert adapter.replicas[tp] == (0, 1)       # rolled back / never applied

    # forced stop on a fresh executor: NO adapter-side cancel, tasks DEAD
    cancelled.clear()
    adapter2 = SpyAdapter({p.topic_partition: p.old_replicas for p in props},
                          latency_polls=10_000)
    ex2 = Executor(adapter2, ExecutorConfig(
        execution_progress_check_interval_ms=5,
        num_concurrent_partition_movements_per_broker=4))
    th2 = threading.Thread(
        target=lambda: done.update(summary2=ex2.execute_proposals(props)))
    th2.start()
    time.sleep(0.05)
    ex2.stop_execution(forced=True)
    th2.join(timeout=30)
    counts2 = done["summary2"]["taskCounts"]["INTER_BROKER_REPLICA_ACTION"]
    assert counts2.get("DEAD", 0) >= 1
    assert cancelled == []                          # forced = drop, no cancel


def test_adapter_without_cancel_still_aborts_in_bookkeeping():
    """An adapter that leaves cancel_reassignments unimplemented must not
    break graceful stop: tasks still transition to ABORTED."""

    class NoCancelAdapter(FakeClusterAdapter):
        def cancel_reassignments(self, tasks):
            raise NotImplementedError

    props = [_proposal("t", i, [0, 1], [2, 1]) for i in range(2)]
    adapter = NoCancelAdapter(
        {p.proposal.topic_partition if hasattr(p, "proposal")
         else p.topic_partition: p.old_replicas for p in props},
        latency_polls=10_000)
    ex = Executor(adapter, ExecutorConfig(
        execution_progress_check_interval_ms=5,
        num_concurrent_partition_movements_per_broker=2))
    done = {}
    th = threading.Thread(
        target=lambda: done.update(summary=ex.execute_proposals(props)))
    th.start()
    time.sleep(0.05)
    ex.stop_execution(forced=False)
    th.join(timeout=30)
    counts = done["summary"]["taskCounts"]["INTER_BROKER_REPLICA_ACTION"]
    assert counts.get("ABORTED", 0) >= 1


def test_hung_adapter_triggers_alerting_threshold_warning(caplog):
    """task.execution.alerting.threshold.ms: a batch stuck in flight past
    the threshold logs the alert (the reference fires a sensor + warning),
    and the round budget eventually marks the stragglers DEAD — driven by a
    genuinely HUNG adapter, not synthetic latency that completes."""
    import logging

    class HungAdapter(FakeClusterAdapter):
        def current_replicas(self, tp):       # never progresses
            return self.replicas.get(tp, ())

    props = [_proposal("t", 0, [0, 1], [2, 1])]
    adapter = HungAdapter({p.topic_partition: p.old_replicas for p in props})
    ex = Executor(adapter, ExecutorConfig(
        execution_progress_check_interval_ms=5,
        max_execution_progress_check_rounds=30,
        task_execution_alerting_threshold_ms=20))
    with caplog.at_level(logging.WARNING,
                         logger="cruise_control_tpu.executor.executor"):
        summary = ex.execute_proposals(props)
    assert summary["timedOut"]
    counts = summary["taskCounts"]["INTER_BROKER_REPLICA_ACTION"]
    assert counts.get("DEAD", 0) == 1
    assert any("alerting threshold" in r.message for r in caplog.records)
