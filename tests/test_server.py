"""Service shell tests: config parsing, facade operations, async machinery,
and the REST endpoints driven end-to-end over a live HTTP server —
modeled on KafkaCruiseControlServletEndpointTest / UserTaskManagerTest /
SessionManagerTest / OperationFutureTest.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from cruise_control_tpu.app import CruiseControlApp
from cruise_control_tpu.common.config import (
    ConfigException,
    CruiseControlConfig,
    load_properties,
)
from cruise_control_tpu.executor.executor import FakeClusterAdapter
from cruise_control_tpu.monitor.load_monitor import StaticMetadataSource
from cruise_control_tpu.monitor.sampler import (
    BrokerMetadata,
    ClusterMetadata,
    PartitionMetadata,
    SyntheticLoadSampler,
)
from cruise_control_tpu.server.async_ops import (
    Purgatory,
    ReviewStatus,
    SessionManager,
    UserTaskManager,
)
from cruise_control_tpu.server import rest

W = 60_000


def _metadata(num_brokers=6, num_parts=30, rf=2, dead=()):
    brokers = [BrokerMetadata(i, rack=f"r{i % 3}", host=f"h{i}",
                              alive=i not in dead) for i in range(num_brokers)]
    parts = []
    for p in range(num_parts):
        reps = tuple((p + j) % num_brokers for j in range(rf))
        leader = next((r for r in reps if r not in dead), reps[0])
        parts.append(PartitionMetadata("T", p, leader=leader, replicas=reps))
    return ClusterMetadata(brokers=brokers, partitions=parts, generation=1)


def _app(metadata=None, overrides=None):
    cfg = CruiseControlConfig({
        "optimizer.engine": "greedy",
        "partition.metrics.window.ms": W,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "execution.progress.check.interval.ms": 1,
        "failed.brokers.file.path": "",
        **(overrides or {})})
    md = metadata or _metadata()
    adapter = FakeClusterAdapter(
        {f"{p.topic}-{p.partition}": tuple(p.replicas)
         for p in md.partitions},
        latency_polls=1)
    app = CruiseControlApp(cfg, StaticMetadataSource(md),
                           SyntheticLoadSampler(seed=4),
                           cluster_adapter=adapter)
    # samples carry synthetic timestamps → pin the monitor clock to match
    # (window aggregation is time-driven; real "now" would expire them)
    app.load_monitor._now = lambda: 4 * W
    for w in range(4):
        app.load_monitor.sample_once(now_ms=w * W + 30_000)
    return app


# ---------------------------------------------------------------- config


def test_config_defaults_and_parse():
    cfg = CruiseControlConfig()
    assert cfg.get("num.partition.metrics.windows") == 5
    assert "RackAwareGoal" in cfg.get("default.goals")
    c2 = CruiseControlConfig({"num.partition.metrics.windows": "7",
                              "self.healing.enabled": "true",
                              "goals": "RackAwareGoal,ReplicaCapacityGoal"})
    assert c2.get("num.partition.metrics.windows") == 7
    assert c2.get("self.healing.enabled") is True
    assert c2.get("goals") == ["RackAwareGoal", "ReplicaCapacityGoal"]


def test_config_validation():
    with pytest.raises(ConfigException):
        CruiseControlConfig({"cpu.capacity.threshold": "1.5"})
    with pytest.raises(ConfigException):
        CruiseControlConfig({"num.partition.metrics.windows": "zero"})


def test_properties_file(tmp_path):
    p = tmp_path / "cc.properties"
    p.write_text("# comment\nwebserver.http.port=9999\n"
                 "default.goals=RackAwareGoal\n")
    cfg = CruiseControlConfig(properties_file=str(p))
    assert cfg.get("webserver.http.port") == 9999
    assert cfg.get("default.goals") == ["RackAwareGoal"]


def test_balancing_constraint_from_config():
    cfg = CruiseControlConfig({"disk.balance.threshold": "1.25",
                               "max.replicas.per.broker": "500"})
    c = cfg.balancing_constraint()
    from cruise_control_tpu.common import resources as res
    assert c.resource_balance_percentage[res.DISK] == 1.25
    assert c.max_replicas_per_broker == 500


# ---------------------------------------------------------------- async ops


def test_user_task_manager_lifecycle():
    utm = UserTaskManager(max_active_tasks=2)
    info = utm.create_task("REBALANCE", "/rebalance", "c1",
                           lambda fut: {"ok": True})
    assert info.future.result(5) == {"ok": True}
    assert utm.get(info.task_id) is not None
    assert utm.get(info.task_id).state.value in ("Completed", "Active")
    tasks = utm.all_tasks()
    assert any(t.task_id == info.task_id for t in tasks)


def test_user_task_manager_limit():
    utm = UserTaskManager(max_active_tasks=1)
    ev = {"hold": True}
    utm.create_task("A", "/a", "c", lambda fut: time.sleep(0.5))
    with pytest.raises(RuntimeError):
        utm.create_task("B", "/b", "c", lambda fut: None)


def test_session_manager_expiry():
    clock = {"t": 0}
    sm = SessionManager(max_expiry_ms=100, now_fn=lambda: clock["t"])
    sm.bind("s1", "task1")
    assert sm.task_for("s1") == "task1"
    clock["t"] = 200
    assert sm.task_for("s1") is None


def test_purgatory_flow():
    p = Purgatory()
    r = p.submit("REBALANCE", "/rebalance?dryrun=false", "alice")
    assert r.status == ReviewStatus.PENDING_REVIEW
    with pytest.raises(ValueError):
        p.take_approved(r.review_id)        # not approved yet
    p.review(r.review_id, approve=True, reason="lgtm")
    taken = p.take_approved(r.review_id)
    assert taken.status == ReviewStatus.SUBMITTED
    with pytest.raises(ValueError):
        p.take_approved(r.review_id)        # single use
    r2 = p.submit("REMOVE_BROKER", "/remove_broker?brokerid=1", "bob")
    p.review(r2.review_id, approve=False, reason="nope")
    assert p.board()[1]["Status"] == "DISCARDED"


# ---------------------------------------------------------------- facade


def test_app_proposals_cache():
    app = _app()
    r1 = app.proposals()
    r2 = app.proposals()
    assert r1 is r2                         # cache hit (same generation)
    r3 = app.proposals(ignore_proposal_cache=True)
    assert r3 is not r1


def test_app_rebalance_execute():
    app = _app()
    out = app.rebalance(dryrun=False)
    assert "execution" in out
    assert out["numReplicaMovements"] >= 0


def test_app_remove_brokers_drains():
    app = _app()
    out = app.remove_brokers([2], dryrun=True)
    # every proposal moving replicas must move them OFF broker 2 and
    # never INTO broker 2
    for p in out["proposals"]:
        assert 2 not in p["newReplicas"]
    assert out["numReplicaMovements"] > 0


def test_app_demote_brokers():
    """DemoteBrokerRunnable parity: leadership leaves the demoted broker and
    replica placement is untouched (demotion is a leadership-only
    operation — DemoteBrokerRunnable.java)."""
    app = _app()
    out = app.demote_brokers([1], dryrun=True)
    for p in out["proposals"]:
        assert p["newReplicas"][0] != 1     # leadership moved off broker 1
        # replica SET preserved: only ordering (leadership) changes
        assert set(p["newReplicas"]) == set(p["oldReplicas"]), p


def test_app_topic_rf_change():
    app = _app()
    out = app.update_topic_replication_factor("T", 3, dryrun=True)
    assert out["numPartitionsChanged"] > 0
    for p in out["proposals"]:
        assert len(p["newReplicas"]) == 3
        assert len(set(p["newReplicas"])) == 3
    out2 = app.update_topic_replication_factor("T", 1, dryrun=True)
    for p in out2["proposals"]:
        assert len(p["newReplicas"]) == 1
        assert p["newReplicas"][0] == p["oldReplicas"][0]  # leader kept


def test_app_self_healing_context():
    app = _app(metadata=_metadata(dead=(3,)))
    out = app.remove_brokers([3], self_healing=True)
    assert "execution" in out               # self-healing executes


# ---------------------------------------------------------------- REST


@pytest.fixture(scope="module")
def server():
    app = _app()
    srv = rest.serve(app, port=0)           # ephemeral port
    yield srv
    srv.shutdown()


def _get(srv, path):
    port = srv.server_address[1]
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(srv, path, data=b""):
    port = srv.server_address[1]
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}", data=data,
                                 method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_rest_state(server):
    code, body = _get(server, "/kafkacruisecontrol/state")
    assert code == 200
    assert set(body) >= {"MonitorState", "ExecutorState", "AnalyzerState",
                         "AnomalyDetectorState"}
    # mesh-policy surface: this server boots without optimizer.mesh.enable,
    # so the sharded path reports inactive
    assert body["AnalyzerState"]["meshDevices"] == 0
    assert body["AnalyzerState"]["shardedPath"] is False
    code, body = _get(server, "/kafkacruisecontrol/state?substates=monitor")
    assert list(body) == ["MonitorState"]


def test_rest_kafka_cluster_state(server):
    code, body = _get(server, "/kafkacruisecontrol/kafka_cluster_state")
    assert code == 200
    assert body["KafkaPartitionState"]["totalPartitions"] == 30


def test_rest_load_and_partition_load(server):
    code, body = _get(server, "/kafkacruisecontrol/load")
    assert code == 200 and len(body["brokers"]) == 6
    code, body = _get(server,
                      "/kafkacruisecontrol/partition_load?entries=5")
    assert code == 200 and len(body["records"]) == 5


def test_rest_proposals_async(server):
    code, body = _get(server, "/kafkacruisecontrol/proposals"
                              "?get_response_timeout_ms=60000")
    assert code == 200
    assert "proposals" in body and "userTaskId" in body


def test_rest_rebalance_dryrun(server):
    code, body = _post(server, "/kafkacruisecontrol/rebalance"
                               "?dryrun=true&get_response_timeout_ms=60000")
    assert code == 200
    # hard goals must end satisfied; balancedness is reported both ways
    assert "balancednessAfter" in body and "proposals" in body
    assert body["violatedGoalsAfter"] == [] or all(
        g not in ("RackAwareGoal", "ReplicaCapacityGoal")
        for g in body["violatedGoalsAfter"])


def test_rest_user_tasks(server):
    _get(server, "/kafkacruisecontrol/proposals?get_response_timeout_ms=60000")
    code, body = _get(server, "/kafkacruisecontrol/user_tasks")
    assert code == 200 and len(body["userTasks"]) >= 1


def test_rest_pause_resume(server):
    server.api.app.load_monitor._state = __import__(
        "cruise_control_tpu.monitor.load_monitor",
        fromlist=["MonitorState"]).MonitorState.RUNNING
    code, body = _post(server, "/kafkacruisecontrol/pause_sampling?reason=test")
    assert code == 200 and body["paused"]
    code, body = _post(server, "/kafkacruisecontrol/resume_sampling")
    assert code == 200 and body["resumed"]


def test_rest_admin_self_healing(server):
    code, body = _post(server, "/kafkacruisecontrol/admin"
                               "?self_healing_for=ALL&enable_self_healing=true")
    assert code == 200
    assert all(body["selfHealingEnabled"].values())


def test_rest_unknown_endpoint(server):
    code, body = _get(server, "/kafkacruisecontrol/nonsense")
    assert code == 404
    assert "validEndpoints" in body


def test_rest_wrong_method(server):
    code, body = _get(server, "/kafkacruisecontrol/rebalance")
    assert code == 405


def test_rest_endpoint_method_matrix(server):
    """Every endpoint x {GET, POST}: the supported method never 404s/405s,
    the wrong method 405s with the list of endpoints valid FOR the method
    attempted, and unknown paths 404 with the full table."""
    assert len(rest.ALL_ENDPOINTS) == 28
    assert set(rest.GET_ENDPOINTS) | set(rest.POST_ENDPOINTS) == set(
        rest.ALL_ENDPOINTS)
    assert not set(rest.GET_ENDPOINTS) & set(rest.POST_ENDPOINTS)
    assert "WHAT_IF" in rest.GET_ENDPOINTS
    assert "RIGHTSIZE" in rest.POST_ENDPOINTS
    for name in rest.ALL_ENDPOINTS:
        path = f"/kafkacruisecontrol/{name.lower()}"
        if name in rest.GET_ENDPOINTS:
            good, bad, bad_list = _get, _post, rest.POST_ENDPOINTS
        else:
            good, bad, bad_list = _post, _get, rest.GET_ENDPOINTS
        code, _ = good(server, path + "?json=true")
        assert code not in (404, 405), (name, code)
        code, body = bad(server, path)
        assert code == 405, (name, code)
        assert body["validEndpoints"] == bad_list
        assert name not in body["validEndpoints"]
    code, body = _get(server, "/kafkacruisecontrol/nope")
    assert code == 404 and body["validEndpoints"] == rest.ALL_ENDPOINTS


def test_rest_two_step_verification():
    app = _app(overrides={"two.step.verification.enabled": True})
    api = rest.RestApi(app)
    code, body = api.dispatch("POST", "REBALANCE", {"dryrun": "true"},
                              request_url="/rebalance?dryrun=true")
    assert code == 202 and "reviewResult" in body
    rid = body["reviewResult"]["Id"]
    code, body = api.dispatch("POST", "REVIEW", {"approve": str(rid)})
    assert code == 200
    # an approval is bound to the endpoint it was reviewed for
    code, body = api.dispatch("POST", "REMOVE_BROKER",
                              {"brokerid": "1", "review_id": str(rid)})
    assert code == 400 and "REBALANCE" in body["errorMessage"]
    code, body = api.dispatch(
        "POST", "REBALANCE",
        {"dryrun": "true", "review_id": str(rid),
         "get_response_timeout_ms": "60000"})
    assert code == 200 and "proposals" in body
    # approval is single-use
    code, body = api.dispatch("POST", "REBALANCE",
                              {"dryrun": "true", "review_id": str(rid)})
    assert code == 400


def test_goal_based_parameter_surface():
    """data_from / use_ready_default_goals / exclusions / verbose honored
    end-to-end (GoalBasedOptimizationParameters surface)."""
    app = _app()
    api = rest.RestApi(app)
    # verbose adds the before/after ClusterModelStats payloads
    code, body = api.dispatch("POST", "REBALANCE",
                              {"dryrun": "true", "verbose": "true",
                               "get_response_timeout_ms": "60000"})
    assert code == 200, body
    assert "clusterModelStatsBeforeOptimization" in body
    assert "goalSummaryDetail" in body
    code, body = api.dispatch("POST", "REBALANCE",
                              {"dryrun": "true",
                               "get_response_timeout_ms": "60000"})
    assert code == 200 and "clusterModelStatsBeforeOptimization" not in body

    # data_from=valid_partitions relaxes the partition-coverage gate
    code, body = api.dispatch("POST", "REBALANCE",
                              {"dryrun": "true",
                               "data_from": "valid_partitions",
                               "get_response_timeout_ms": "60000"})
    assert code == 200, body

    # exclude_recently_removed_brokers: a drained broker cannot receive
    # replicas on the next rebalance
    app.executor.record_history(removed_brokers=[1])
    code, body = api.dispatch("POST", "REBALANCE",
                              {"dryrun": "true", "verbose": "true",
                               "exclude_recently_removed_brokers": "true",
                               "get_response_timeout_ms": "60000"})
    assert code == 200, body
    for p in body["proposals"]:
        added = set(p["newReplicas"]) - set(p["oldReplicas"])
        assert 1 not in added, p

    # use_ready_default_goals with full window coverage = all default goals
    code, body = api.dispatch("GET", "PROPOSALS",
                              {"use_ready_default_goals": "true",
                               "ignore_proposal_cache": "true",
                               "get_response_timeout_ms": "60000"})
    assert code == 200, body


def test_operation_progress_steps_populated():
    """In-flight 202 responses carry real OperationProgress steps
    (async/progress/OperationProgress.java), not an empty list."""
    app = _app()
    api = rest.RestApi(app)
    # zero timeout forces the in-progress path; then poll to completion
    code, body = api.dispatch("POST", "REBALANCE",
                              {"dryrun": "true",
                               "get_response_timeout_ms": "0"})
    tid = body["userTaskId"]
    assert code in (200, 202)
    deadline = time.time() + 120
    steps = []
    while time.time() < deadline:
        code, body = api.dispatch("POST", "REBALANCE",
                                  {"dryrun": "true", "user_task_id": tid,
                                   "get_response_timeout_ms": "2000"})
        info = api.user_tasks.get(tid)
        steps = info.future.progress.snapshot()
        if code == 200:
            break
    assert code == 200, body
    descs = [s["step"] for s in steps]
    assert any("cluster model" in d for d in descs), descs
    assert any("Optimizing" in d for d in descs), descs
    assert any("proposals" in d for d in descs), descs


def test_proposal_precompute_tick_warms_cache():
    """GoalOptimizer precompute-loop parity (GoalOptimizer.java:126-176):
    the tick computes when the cache is cold/stale, skips when fresh, and a
    subsequent PROPOSALS request is served from the warmed cache."""
    app = _app()
    assert app._cache_is_fresh() is False
    assert app.precompute_tick() is True          # cold → computes
    assert app._cache_is_fresh() is True
    assert app.precompute_tick() is False         # fresh → skips
    cached = app._proposal_cache
    r = app.proposals()
    assert r is cached.result                     # request hits the cache
    # a new metadata generation invalidates the cache for the next tick
    import dataclasses as _dc
    src = app._metadata_source
    src.metadata = _dc.replace(src.metadata,
                               generation=src.metadata.generation + 1)
    assert app._cache_is_fresh() is False
    assert app.precompute_tick() is True          # stale → recomputes


def test_verbose_response_has_per_broker_stats():
    """response/stats BrokerStats parity: verbose proposals carry per-broker
    before/after rows; total replica counts are conserved."""
    app = _app()
    r = app.proposals(ignore_proposal_cache=True)
    body = r.to_json(verbose=True)
    before = body["loadBeforeOptimization"]["brokers"]
    after = body["loadAfterOptimization"]["brokers"]
    assert len(before) == len(after) == 6
    assert sum(b["Replicas"] for b in before) == sum(
        b["Replicas"] for b in after) == 60
    assert sum(b["Leaders"] for b in after) == 30
    for row in after:
        assert {"Broker", "BrokerState", "CpuPct", "DiskMB", "NwInRate",
                "NwOutRate", "PnwOutRate"} <= set(row)
    # non-verbose responses stay lean
    assert "loadBeforeOptimization" not in r.to_json(verbose=False)


def test_tail_parameters_surface():
    """The last four ParameterUtils params: min_valid_partition_ratio,
    avg_load, super_verbose, skip_rack_awareness_check."""
    app = _app()
    api = rest.RestApi(app)

    # min_valid_partition_ratio: an impossible per-request ratio fails the
    # completeness gate; an explicit 0.0 passes it
    code, body = api.dispatch("GET", "PROPOSALS",
                              {"ignore_proposal_cache": "true",
                               "min_valid_partition_ratio": "1.5",
                               "get_response_timeout_ms": "60000"})
    assert code == 500 and ("ratio" in body["errorMessage"]
                            or "valid windows" in body["errorMessage"]), body
    code, body = api.dispatch("GET", "PROPOSALS",
                              {"ignore_proposal_cache": "true",
                               "min_valid_partition_ratio": "0.0",
                               "get_response_timeout_ms": "60000"})
    assert code == 200, body

    # avg_load=true overrides max_load (PartitionLoadParameters)
    code, body_max = api.dispatch("GET", "PARTITION_LOAD",
                                  {"max_load": "true", "entries": "5"})
    assert code == 200
    code, body_avg = api.dispatch("GET", "PARTITION_LOAD",
                                  {"max_load": "true", "avg_load": "true",
                                   "entries": "5"})
    assert code == 200

    # super_verbose STATE carries sample-extrapolation flaws and the LR
    # model state (CruiseControlState.writeSuperVerbose)
    code, state = api.dispatch("GET", "STATE", {"super_verbose": "true"})
    assert code == 200
    assert "extrapolatedMetricSamples" in state["MonitorState"]
    assert "linearRegressionModelState" in state["MonitorState"]
    code, state = api.dispatch("GET", "STATE", {})
    assert "extrapolatedMetricSamples" not in state["MonitorState"]

    # skip_rack_awareness_check: RF above the alive-rack count is rejected
    # unless skipped (_metadata uses 3 racks)
    code, body = api.dispatch("POST", "TOPIC_CONFIGURATION",
                              {"topic": "T", "replication_factor": "5",
                               "get_response_timeout_ms": "60000"})
    assert code == 500 and "rack" in body["errorMessage"], body
    code, body = api.dispatch("POST", "TOPIC_CONFIGURATION",
                              {"topic": "T", "replication_factor": "5",
                               "skip_rack_awareness_check": "true",
                               "get_response_timeout_ms": "60000"})
    assert code == 200, body


def test_kafka_assigner_mode_on_proposals_and_remove():
    """KAFKA_ASSIGNER_MODE_PARAM is valid on PROPOSALS and
    ADD/REMOVE_BROKER (AddedOrRemovedBrokerParameters.java:32,
    ProposalsParameters.java:36), not just REBALANCE. REMOVE with the flag
    drains the removed brokers via the deterministic assigner placement."""
    app = _app()
    api = rest.RestApi(app)
    code, body = api.dispatch("GET", "PROPOSALS",
                              {"kafka_assigner": "true",
                               "get_response_timeout_ms": "60000"})
    assert code == 200, body
    assert body["mode"] == "kafka_assigner"

    code, body = api.dispatch("POST", "REMOVE_BROKER",
                              {"brokerid": "2", "kafka_assigner": "true",
                               "dryrun": "true",
                               "get_response_timeout_ms": "60000"})
    assert code == 200, body
    assert body["mode"] == "kafka_assigner"
    for p in body["proposals"]:
        assert 2 not in p["newReplicas"], p     # drained off broker 2

    code, body = api.dispatch("POST", "ADD_BROKER",
                              {"brokerid": "0", "kafka_assigner": "true",
                               "dryrun": "true",
                               "get_response_timeout_ms": "60000"})
    assert code == 200, body


def test_session_binds_repeated_request_to_same_task():
    """UserTaskManager.getOrCreateUserTask semantics: the same session
    repeating the same async request (same endpoint + parameters) gets its
    ORIGINAL task — in flight or completed (repetition is the polling
    pattern, and the finished result must stay deliverable); different
    parameters or a different session create a new one. Replay staleness
    is bounded by the SessionManager expiry."""
    from cruise_control_tpu.server import rest
    app = _app()
    api = rest.RestApi(app)
    try:
        # 1ms timeout: the first dispatch returns 202 with the op in flight
        p = {"get_response_timeout_ms": "1"}
        code1, body1 = api.dispatch("GET", "PROPOSALS", dict(p),
                                    client_id="10.0.0.5", session_id="sess-a")
        code2, body2 = api.dispatch("GET", "PROPOSALS", dict(p),
                                    client_id="10.0.0.5", session_id="sess-a")
        assert body1["userTaskId"] == body2["userTaskId"]
        # different params -> a different task (polling-only params ignored)
        code3, body3 = api.dispatch(
            "GET", "PROPOSALS",
            {**p, "ignore_proposal_cache": "true"}, client_id="10.0.0.5",
            session_id="sess-a")
        assert body3["userTaskId"] != body1["userTaskId"]
        # different session -> a different task
        code4, body4 = api.dispatch("GET", "PROPOSALS", dict(p),
                                    client_id="10.0.0.5", session_id="sess-b")
        assert body4["userTaskId"] != body1["userTaskId"]
        # tasks are attributed to the request ORIGIN, not the session
        assert api.user_tasks.get(body1["userTaskId"]).client_id == "10.0.0.5"
        # after completion, the repeat still delivers the ORIGINAL task's
        # result (bounded by session expiry) — the poller must not trigger
        # a silent re-execution between its polls
        info = api.user_tasks.get(body1["userTaskId"])
        info.future.result(timeout=120)
        code5, body5 = api.dispatch("GET", "PROPOSALS", dict(p),
                                    client_id="10.0.0.5", session_id="sess-a")
        assert code5 == 200
        assert body5["userTaskId"] == body1["userTaskId"]
        # once the session binding expires, the same request runs anew
        api.sessions._expiry = 0
        code6, body6 = api.dispatch("GET", "PROPOSALS", dict(p),
                                    client_id="10.0.0.5", session_id="sess-a")
        assert body6["userTaskId"] != body1["userTaskId"]
    finally:
        api.close()


def test_escape_kernel_warm_fires_once_on_real_size_models(monkeypatch):
    """The first default-goal proposal computation on a model above the
    tiny-CPU bound must schedule the escape-kernel warm exactly once (on
    a background thread — the compute gate is held here); tiny models
    must never schedule it. The SCHEDULING decision is asserted through
    the synchronous ``_escape_kernels_warmed`` flag (the spy runs on a
    daemon thread, so bare call-list asserts would race it)."""
    import threading as _threading

    from cruise_control_tpu.analyzer import optimizer as OPT_mod

    calls = []
    done = _threading.Event()

    def _spy(topo, assign, **kw):
        calls.append((topo.num_brokers, topo.num_replicas, sorted(kw)))
        done.set()

    monkeypatch.setattr(OPT_mod, "warm_kernels", _spy)

    # tiny model (test fixture is far below TINY_CPU_LIMIT): the compute
    # path runs but never SCHEDULES a warm — asserted via the flag, which
    # _compute_and_cache sets synchronously before spawning the thread
    app = _app()
    app.proposals()
    assert app._escape_kernels_warmed is False
    assert not done.is_set()

    # with the bound lowered the fixture counts as real-size: the first
    # compute schedules the warm; a SECOND pass through the compute path
    # (cache invalidated, so _compute_and_cache re-runs) must not
    monkeypatch.setattr(OPT_mod, "TINY_CPU_LIMIT", 1)
    app2 = _app()
    app2.proposals()
    assert app2._escape_kernels_warmed is True
    assert done.wait(timeout=5), "warm thread never ran"
    app2._proposal_cache = None       # force the next call to recompute
    app2.proposals()
    assert len(calls) == 1            # once per app, not once per compute
    nb, nr, kws = calls[0]
    assert (nb, nr) == (6, 60)        # the served model's shape
    assert "mesh" in kws and "constraint" in kws and "goal_names" in kws
