"""Library-level fake ``kafka`` module driving the kafka-python binding seam.

Every other test injects fakes ABOVE the binding (fake adapters/producers),
leaving the default construction, serde, batched alter-configs and error
paths of ``kafka_adapter.py`` and ``monitor/sample_store.py`` unexecuted.
This module monkeypatches a faithful in-memory ``kafka`` package into
``sys.modules`` and drives those exact code paths — the JVM-less analogue of
the reference's embedded-broker tests (``ExecutorTest.java:58``,
``KafkaSampleStoreTest``).
"""

import json
import sys
import types

import pytest

from cruise_control_tpu.common.config import CruiseControlConfig


# ---------------------------------------------------------------------------
# The fake kafka-python package
# ---------------------------------------------------------------------------


class _FakeBrokerState:
    """Shared in-memory cluster behind the fake clients."""

    def __init__(self):
        self.brokers = [
            {"node_id": 0, "host": "h0", "rack": "r0"},
            {"node_id": 1, "host": "h1", "rack": "r1"},
            {"node_id": 2, "host": "h2", "rack": "r0"},
        ]
        # topic -> partition -> {"replicas": [...], "leader": int}
        self.topics = {
            "T": {0: {"replicas": [0, 1], "leader": 0},
                  1: {"replicas": [1, 2], "leader": 1}},
        }
        self.topic_configs = {}       # (rtype:int, name) -> {k: v} dynamic
        self.records = {}             # topic -> [(key, value-bytes)]
        self.in_progress = {}         # (topic, part) -> new replicas
        self.log_dirs = {0: {"/d0": {"error_code": 0}},
                         1: {"/d0": {"error_code": 1}}}
        self.logdir_moves = []
        self.describe_configs_error = False
        self.created_topics = {}


def make_fake_kafka(state: _FakeBrokerState):
    kafka = types.ModuleType("kafka")
    admin_mod = types.ModuleType("kafka.admin")

    class ConfigResourceType:
        class _V:
            def __init__(self, v):
                self.value = v
        BROKER = _V(4)
        TOPIC = _V(2)

    class ConfigResource:
        def __init__(self, resource_type, name, configs=None):
            self.resource_type = resource_type
            self.name = name
            self.configs = configs

    class NewTopic:
        def __init__(self, name, num_partitions, replication_factor,
                     topic_configs=None):
            self.name = name
            self.num_partitions = num_partitions
            self.replication_factor = replication_factor
            self.topic_configs = topic_configs

    class KafkaAdminClient:
        def __init__(self, bootstrap_servers=None, **_):
            assert bootstrap_servers, "bootstrap_servers must be threaded"
            self._s = state

        # -- metadata ---------------------------------------------------
        def describe_cluster(self):
            return {"brokers": list(self._s.brokers)}

        def describe_topics(self, topics=None):
            names = topics if topics is not None else list(self._s.topics)
            out = []
            for t in names:
                parts = self._s.topics.get(t, {})
                out.append({"topic": t, "partitions": [
                    {"partition": p, "leader": info["leader"],
                     "replicas": list(info["replicas"]),
                     "isr": list(info["replicas"]),
                     "offline_replicas": []}
                    for p, info in sorted(parts.items())]})
            return out

        # -- reassignment / election ------------------------------------
        def alter_partition_reassignments(self, assignments):
            for (t, p), reps in assignments.items():
                if reps is None:                      # KIP-455 cancel
                    self._s.in_progress.pop((t, p), None)
                    continue
                info = self._s.topics.setdefault(t, {}).setdefault(
                    p, {"replicas": [], "leader": -1})
                if set(reps) != set(info["replicas"]):
                    # data actually moves: stays visibly in progress;
                    # a pure reorder (the PLE pre-step) completes
                    # immediately, as on a real broker
                    self._s.in_progress[(t, p)] = list(reps)
                info["replicas"] = list(reps)
                if info["leader"] not in reps:
                    info["leader"] = reps[0]

        def list_partition_reassignments(self):
            return dict(self._s.in_progress)

        def perform_leader_election(self, election_type, partitions):
            assert election_type == "PREFERRED"
            for (t, p) in partitions:
                info = self._s.topics[t][p]
                info["leader"] = info["replicas"][0]

        # -- configs ----------------------------------------------------
        def describe_configs(self, config_resources):
            if self._s.describe_configs_error:
                entry = (41, "NOT_CONTROLLER", 4, "0", [])
                return [types.SimpleNamespace(resources=[entry])]
            out = []
            for r in config_resources:
                rtype = int(r.resource_type.value)
                cfgs = self._s.topic_configs.get((rtype, str(r.name)), {})
                entries = [(k, v, False, 1) for k, v in cfgs.items()]
                # plus a STATIC (source 5) entry that must NOT be merged
                entries.append(("static.key", "static-value", False, 5))
                out.append(types.SimpleNamespace(
                    resources=[(0, None, rtype, str(r.name), entries)]))
            return out

        def alter_configs(self, resources):
            for r in resources:                       # REPLACE semantics
                rtype = int(r.resource_type.value)
                self._s.topic_configs[(rtype, str(r.name))] = dict(
                    r.configs or {})

        # -- logdirs ----------------------------------------------------
        def describe_log_dirs(self, **kwargs):
            if "timeout_ms" in kwargs:
                raise TypeError("unexpected keyword 'timeout_ms'")
            return dict(self._s.log_dirs)

        def alter_replica_log_dirs(self, moves):
            self._s.logdir_moves.append(dict(moves))

        def create_topics(self, new_topics):
            for t in new_topics:
                if t.name in self._s.created_topics:
                    raise RuntimeError("TopicExistsError")
                self._s.created_topics[t.name] = t

    class KafkaProducer:
        def __init__(self, bootstrap_servers=None, value_serializer=None,
                     **_):
            assert bootstrap_servers
            self._ser = value_serializer or (lambda v: v)
            self.flushed = 0

        def send(self, topic, value, key=None):
            state.records.setdefault(topic, []).append((key, self._ser(value)))

        def flush(self):
            self.flushed += 1

        def close(self):
            pass

    class KafkaConsumer:
        def __init__(self, topic, bootstrap_servers=None,
                     value_deserializer=None, **_):
            assert bootstrap_servers
            self._msgs = [types.SimpleNamespace(
                key=k, value=(value_deserializer or (lambda b: b))(v))
                for k, v in state.records.get(topic, [])]
            self.closed = False

        def __iter__(self):
            return iter(self._msgs)

        def close(self):
            self.closed = True

    admin_mod.ConfigResource = ConfigResource
    admin_mod.ConfigResourceType = ConfigResourceType
    admin_mod.NewTopic = NewTopic
    kafka.admin = admin_mod
    kafka.KafkaAdminClient = KafkaAdminClient
    kafka.KafkaProducer = KafkaProducer
    kafka.KafkaConsumer = KafkaConsumer
    return kafka, admin_mod


@pytest.fixture
def fake_kafka(monkeypatch):
    state = _FakeBrokerState()
    kafka, admin_mod = make_fake_kafka(state)
    monkeypatch.setitem(sys.modules, "kafka", kafka)
    monkeypatch.setitem(sys.modules, "kafka.admin", admin_mod)
    return state


def _cfg(extra=None):
    return CruiseControlConfig({"bootstrap.servers": "fake:9092",
                                **(extra or {})})


# ---------------------------------------------------------------------------
# KafkaMetadataSource / adapter paths (kafka_adapter.py:58-430)
# ---------------------------------------------------------------------------


def test_metadata_source_via_fake_kafka(fake_kafka):
    from cruise_control_tpu.kafka_adapter import KafkaMetadataSource
    src = KafkaMetadataSource(_cfg())
    md = src.get_metadata()
    assert {b.broker_id for b in md.brokers} == {0, 1, 2}
    assert {(p.topic, p.partition) for p in md.partitions} == {
        ("T", 0), ("T", 1)}
    assert md.generation == 1
    assert src.get_metadata().generation == 2


def test_adapter_reassign_ple_cancel_and_progress(fake_kafka):
    from cruise_control_tpu.executor.tasks import ExecutionTask, TaskType
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    from cruise_control_tpu.kafka_adapter import KafkaClusterAdapter

    ad = KafkaClusterAdapter(_cfg())
    move = ExecutionTask(1, ExecutionProposal(
        topic="T", partition=0, old_leader=0,
        old_replicas=(0, 1), new_replicas=(2, 1), data_size=1.0),
        task_type=TaskType.INTER_BROKER_REPLICA_ACTION)
    ad.execute_replica_reassignments([move])
    assert ad.current_replicas("T-0") == (2, 1)
    assert ad.in_progress_reassignments() == {"T-0"}

    # leadership-only proposal: the two-step PLE must write the reorder
    # first, then elect — the new leader is the new list head
    lead = ExecutionTask(2, ExecutionProposal(
        topic="T", partition=1, old_leader=1,
        old_replicas=(1, 2), new_replicas=(2, 1), data_size=1.0),
        task_type=TaskType.LEADER_ACTION)
    ad.execute_preferred_leader_elections([lead])
    assert ad.current_replicas("T-1") == (2, 1)
    assert ad.current_leader("T-1") == 2

    ad.cancel_reassignments([move])
    assert ad.in_progress_reassignments() == set()


def test_adapter_throttle_merge_preserves_unrelated_dynamic_config(
        fake_kafka):
    """kafka-python's legacy AlterConfigs REPLACES a resource's dynamic
    config — the adapter must merge with current overrides so an unrelated
    dynamic setting survives a throttle set/clear cycle, and the STATIC
    source-5 entry must never be promoted to a dynamic override."""
    from cruise_control_tpu.kafka_adapter import KafkaClusterAdapter
    ad = KafkaClusterAdapter(_cfg())
    fake_kafka.topic_configs[(4, "1")] = {"unrelated.setting": "7"}

    ad.set_broker_throttle_rate([1], 1000)
    cfg = fake_kafka.topic_configs[(4, "1")]
    assert cfg["leader.replication.throttled.rate"] == "1000"
    assert cfg["unrelated.setting"] == "7"       # merge, not wipe
    assert "static.key" not in cfg               # source 5 never merged

    ad.clear_broker_throttle_rate([1])
    cfg = fake_kafka.topic_configs[(4, "1")]
    assert "leader.replication.throttled.rate" not in cfg
    assert cfg["unrelated.setting"] == "7"


def test_adapter_topic_throttled_replicas_batch(fake_kafka):
    from cruise_control_tpu.kafka_adapter import KafkaClusterAdapter
    ad = KafkaClusterAdapter(_cfg())
    ad.set_topic_throttled_replicas("T", ["0:0", "1:1"], ["0:2"])
    cfg = fake_kafka.topic_configs[(2, "T")]
    assert cfg["leader.replication.throttled.replicas"] == "0:0,1:1"
    ad.clear_topic_throttled_replicas("T")
    cfg = fake_kafka.topic_configs[(2, "T")]
    assert "leader.replication.throttled.replicas" not in cfg


def test_adapter_describe_configs_error_aborts_update(fake_kafka):
    """An unreadable resource must abort (merging with an empty read would
    silently wipe unrelated dynamic settings)."""
    from cruise_control_tpu.kafka_adapter import KafkaClusterAdapter
    ad = KafkaClusterAdapter(_cfg())
    fake_kafka.describe_configs_error = True
    with pytest.raises(RuntimeError, match="DescribeConfigs failed"):
        ad.set_broker_throttle_rate([0], 500)


def test_adapter_describe_logdirs_and_moves(fake_kafka):
    from cruise_control_tpu.kafka_adapter import KafkaClusterAdapter
    ad = KafkaClusterAdapter(_cfg(
        {"logdir.response.timeout.ms": 1234}))
    # fake raises TypeError on timeout_ms: the stock-client fallback path
    dirs = ad.describe_logdirs()
    assert dirs == {0: {"/d0": True}, 1: {"/d0": False}}

    from cruise_control_tpu.analyzer.intra_broker import LogdirMove
    mv = LogdirMove(topic="T", partition=0, broker_id=0,
                    from_logdir="/d0", to_logdir="/d1", data_size=1.0)
    ad.alter_replica_logdirs([mv])
    assert fake_kafka.logdir_moves == [{("T", 0, 0): "/d1"}]


# ---------------------------------------------------------------------------
# Reporter transport + sampler through the fake wire
# ---------------------------------------------------------------------------


def test_metrics_transport_to_sampler_roundtrip(fake_kafka):
    from cruise_control_tpu.kafka_adapter import (
        KafkaMetricsTopicSampler, KafkaMetricsTransport, METRICS_TOPIC)
    from cruise_control_tpu.reporter import CruiseControlMetric

    transport = KafkaMetricsTransport(_cfg())
    transport.send([
        CruiseControlMetric("ALL_TOPIC_BYTES_IN", 5_000, 0, 100.0),
        CruiseControlMetric("TOPIC_BYTES_IN", 5_000, 0, 60.0, topic="T"),
        CruiseControlMetric("PARTITION_SIZE", 5_000, 0, 42.0,
                            topic="T", partition=0),
    ])
    assert len(fake_kafka.records[METRICS_TOPIC]) == 3

    sampler = KafkaMetricsTopicSampler(_cfg())
    from cruise_control_tpu.kafka_adapter import KafkaMetadataSource
    md = KafkaMetadataSource(_cfg()).get_metadata()
    psamples, bsamples = sampler.get_samples(md, 0, 10_000)
    assert any(b.broker_id == 0 and b.leader_bytes_in == 100.0
               for b in bsamples)
    assert any(p.topic == "T" and p.partition == 0 for p in psamples)


# ---------------------------------------------------------------------------
# KafkaSampleStore DEFAULT construction (monitor/sample_store.py:94-123)
# ---------------------------------------------------------------------------


def test_sample_store_default_construction_roundtrip(fake_kafka):
    import numpy as np
    from cruise_control_tpu.monitor.sample_store import KafkaSampleStore
    from cruise_control_tpu.monitor import metricdef as mdf
    from cruise_control_tpu.monitor.sampler import (
        BrokerMetricSample, PartitionMetricSample)

    store = KafkaSampleStore(
        _cfg({"sample.store.topic.replication.factor": 1}))
    # topic bootstrap ran with the configured retention
    assert set(fake_kafka.created_topics) == {
        store.partition_topic, store.broker_topic}
    assert "retention.ms" in (
        fake_kafka.created_topics[store.partition_topic].topic_configs)

    metrics = np.full(mdf.NUM_MODEL_METRICS, np.nan)
    metrics[mdf.ModelMetric.CPU_USAGE] = 0.5
    store.store_samples(
        [PartitionMetricSample(topic="T", partition=0, leader_broker=0,
                               time_ms=1_000, metrics=metrics)],
        [BrokerMetricSample(broker_id=0, time_ms=1_000, cpu_util=0.4,
                            leader_bytes_in=10.0, leader_bytes_out=5.0,
                            replication_bytes_in=2.0,
                            replication_bytes_out=1.0)])
    # a corrupt record must be skipped on replay, not crash it
    fake_kafka.records[store.partition_topic].append((b"junk", b"{not json"))

    store2 = KafkaSampleStore(
        _cfg({"sample.store.topic.replication.factor": 1}))
    got_p, got_b = [], []
    n = store2.load_samples(got_p.append, got_b.append)
    assert n == 2
    assert got_p[0].topic == "T" and got_p[0].leader_broker == 0
    assert got_b[0].broker_id == 0 and got_b[0].cpu_util == 0.4
