"""Detector tests, modeled on AnomalyDetectorTest / SelfHealingNotifierTest
(fake time, queue/handler assertions) and BrokerFailureDetectorTest
(persisted failure record)."""

import numpy as np
import pytest

from cruise_control_tpu.detector.anomalies import (
    AnomalyAction,
    AnomalyType,
    BrokerFailures,
    GoalViolations,
    SelfHealingNotifier,
    SlackSelfHealingNotifier,
)
from cruise_control_tpu.detector.detectors import (
    AnomalyDetectorService,
    BrokerFailureDetector,
    DiskFailureDetector,
    GoalViolationDetector,
    MetricAnomalyDetector,
    SlowBrokerFinder,
    percentile_anomalies,
)
from cruise_control_tpu.monitor.load_monitor import LoadMonitor, StaticMetadataSource
from cruise_control_tpu.monitor.sampler import (
    BrokerMetadata,
    ClusterMetadata,
    PartitionMetadata,
    SyntheticLoadSampler,
)

W = 60_000


class FakeTime:
    def __init__(self, t0=0):
        self.t = t0

    def __call__(self):
        return self.t


def _metadata(dead=()):
    brokers = [BrokerMetadata(i, rack=f"r{i % 2}", host=f"h{i}",
                              alive=i not in dead) for i in range(4)]
    parts = [PartitionMetadata("T", p, leader=(p % 4 if p % 4 not in dead
                                               else (p + 1) % 4),
                               replicas=(p % 4, (p + 1) % 4))
             for p in range(8)]
    return ClusterMetadata(brokers=brokers, partitions=parts, generation=1)


def test_broker_failure_detector_persistence(tmp_path):
    clock = FakeTime(1000)
    path = str(tmp_path / "failed_brokers.json")
    src = StaticMetadataSource(_metadata(dead=(2,)))
    d = BrokerFailureDetector(src, persist_path=path, now_fn=clock)
    a = d.detect()
    assert a is not None and a.failed_brokers_by_time == {2: 1000}
    # restart: record survives, original failure time kept
    clock.t = 5000
    d2 = BrokerFailureDetector(src, persist_path=path, now_fn=clock)
    a2 = d2.detect()
    assert a2.failed_brokers_by_time == {2: 1000}
    # broker recovers: record cleared
    src.metadata = _metadata()
    assert d2.detect() is None


def test_self_healing_notifier_thresholds():
    clock = FakeTime(0)
    n = SelfHealingNotifier(broker_failure_alert_threshold_ms=100,
                            self_healing_threshold_ms=200,
                            enabled={AnomalyType.BROKER_FAILURE: True},
                            now_fn=clock)
    a = BrokerFailures(AnomalyType.BROKER_FAILURE, 0,
                       failed_brokers_by_time={1: 0})
    clock.t = 50
    r = n.on_anomaly(a)
    assert r.action == AnomalyAction.CHECK and r.delay_ms == 50
    clock.t = 150
    r = n.on_anomaly(a)
    assert r.action == AnomalyAction.CHECK   # alerted, waiting for fix window
    assert n.alerts and n.alerts[-1]["autoFixTriggered"] is False
    clock.t = 250
    r = n.on_anomaly(a)
    assert r.action == AnomalyAction.FIX
    assert n.alerts[-1]["autoFixTriggered"] is True


def test_self_healing_notifier_disabled_ignores():
    clock = FakeTime(1_000_000)
    n = SelfHealingNotifier(now_fn=clock)
    a = BrokerFailures(AnomalyType.BROKER_FAILURE, 0,
                       failed_brokers_by_time={1: 0})
    assert n.on_anomaly(a).action == AnomalyAction.IGNORE
    g = GoalViolations(AnomalyType.GOAL_VIOLATION, 0,
                       fixable_violated_goals=["RackAwareGoal"])
    assert n.on_anomaly(g).action == AnomalyAction.IGNORE
    n.set_self_healing_for(AnomalyType.GOAL_VIOLATION, True)
    assert n.on_anomaly(g).action == AnomalyAction.FIX


def test_slack_notifier_posts():
    posts = []
    n = SlackSelfHealingNotifier(
        webhook_url="http://hook", channel="#ops",
        post_fn=lambda url, payload: posts.append((url, payload)),
        enabled={AnomalyType.GOAL_VIOLATION: True})
    g = GoalViolations(AnomalyType.GOAL_VIOLATION, 0,
                       fixable_violated_goals=["RackAwareGoal"])
    n.on_anomaly(g)
    assert posts and posts[0][0] == "http://hook"


def test_percentile_finder():
    hist = np.full(20, 10.0)
    assert percentile_anomalies(hist, 16.0) is not None   # > P95 * 1.5
    assert percentile_anomalies(hist, 11.0) is None
    assert percentile_anomalies(hist, 1.0) is not None    # < P2 * 0.2


def test_metric_anomaly_detector():
    history = {0: {"cpu": np.array([10.0] * 10 + [50.0])},
               1: {"cpu": np.array([10.0] * 11)}}
    d = MetricAnomalyDetector(lambda: history, now_fn=FakeTime(1))
    found = d.detect()
    assert len(found) == 1 and found[0].broker_id == 0


def test_disk_failure_detector():
    d = DiskFailureDetector(lambda: {0: {"/d1": True, "/d2": False},
                                     1: {"/d1": True}}, now_fn=FakeTime(1))
    a = d.detect()
    assert a.failed_disks_by_broker == {0: ["/d2"]}


def test_slow_broker_finder_escalation():
    clock = FakeTime(0)
    flush = {b: [10.0] * 8 for b in range(3)}
    bytes_in = {b: [1000.0] * 8 for b in range(3)}

    def hist():
        return {b: {"flush_time": flush[b], "bytes_in": bytes_in[b]}
                for b in range(3)}

    f = SlowBrokerFinder(hist, score_threshold=2, removal_threshold=4,
                         now_fn=clock)
    assert f.detect() is None
    # broker 2 becomes persistently slow
    for i in range(4):
        flush[2] = flush[2] + [500.0]
        bytes_in[2] = bytes_in[2] + [1000.0]
        for b in (0, 1):
            flush[b] = flush[b] + [10.0]
            bytes_in[b] = bytes_in[b] + [1000.0]
        clock.t += 1000
        a = f.detect()
    assert a is not None and 2 in a.slow_brokers_by_time
    assert a.remove_slow_brokers    # escalated past removal threshold


def test_goal_violation_detector_end_to_end():
    md = _metadata(dead=(1,))
    lm = LoadMonitor(StaticMetadataSource(md), SyntheticLoadSampler(seed=3),
                     num_windows=3, window_ms=W)
    for w in range(4):
        lm.sample_once(now_ms=w * W + 30_000)
    d = GoalViolationDetector(lm, now_fn=FakeTime(4 * W))
    a = d.detect()
    assert a is not None
    assert "OfflineReplicas" in a.fixable_violated_goals


class _Ctx:
    def __init__(self):
        self.calls = []

    def rebalance(self, **kw):
        self.calls.append("rebalance")
        return {"ok": True}

    def remove_brokers(self, ids, **kw):
        self.calls.append(("remove", tuple(ids)))
        return {"ok": True}

    def demote_brokers(self, ids, **kw):
        self.calls.append(("demote", tuple(ids)))
        return {"ok": True}

    def fix_offline_replicas(self, **kw):
        self.calls.append("fix_offline")
        return {"ok": True}


def test_detector_service_fix_path():
    clock = FakeTime(1_000_000)
    notifier = SelfHealingNotifier(
        broker_failure_alert_threshold_ms=0, self_healing_threshold_ms=0,
        enabled={t: True for t in AnomalyType}, now_fn=clock)
    ctx = _Ctx()
    failures = {"v": BrokerFailures(AnomalyType.BROKER_FAILURE, 0,
                                    failed_brokers_by_time={3: 0})}
    svc = AnomalyDetectorService(
        notifier, context=ctx,
        detectors={"broker_failure": lambda: failures["v"]},
        now_fn=clock)
    assert svc.sweep() == 1
    assert svc.handle_pending() == 1
    assert ctx.calls == [("remove", (3,))]
    assert svc.metrics["fixes_triggered"] == 1
    snap = svc.state_snapshot()
    assert snap["recentAnomalies"][-1]["action"] == "FIX"


def test_detector_service_delays_during_execution():
    clock = FakeTime(1_000_000)
    notifier = SelfHealingNotifier(enabled={t: True for t in AnomalyType},
                                   now_fn=clock)
    ctx = _Ctx()
    svc = AnomalyDetectorService(
        notifier, context=ctx, has_ongoing_execution=lambda: True,
        detectors={}, now_fn=clock)
    svc.enqueue(GoalViolations(AnomalyType.GOAL_VIOLATION, 0,
                               fixable_violated_goals=["RackAwareGoal"]))
    svc.handle_pending()
    assert ctx.calls == []
    assert svc.history[-1]["action"] == "DELAYED_ONGOING_EXECUTION"


def test_anomaly_requeued_and_rechecked_after_execution():
    """Anomalies deferred by an ongoing execution are re-queued with a delay
    and handled once it finishes (AnomalyDetector.java:391-404), not dropped."""
    clock = FakeTime(1_000_000)
    notifier = SelfHealingNotifier(enabled={t: True for t in AnomalyType},
                                   now_fn=clock)
    ctx = _Ctx()
    executing = {"on": True}
    svc = AnomalyDetectorService(
        notifier, context=ctx, has_ongoing_execution=lambda: executing["on"],
        detectors={}, recheck_delay_ms=10_000, now_fn=clock)
    svc.enqueue(GoalViolations(AnomalyType.GOAL_VIOLATION, 0,
                               fixable_violated_goals=["RackAwareGoal"]))
    svc.handle_pending()
    assert ctx.calls == []
    assert svc.history[-1]["action"] == "DELAYED_ONGOING_EXECUTION"
    # execution still running at the re-check: deferred again
    clock.t += 10_001
    svc.handle_pending()
    assert ctx.calls == []
    # execution done but delay not yet elapsed: stays queued, no action
    executing["on"] = False
    svc.handle_pending()
    assert ctx.calls == []
    clock.t += 10_001
    assert svc.handle_pending() == 1
    assert ctx.calls == ["rebalance"]


def test_enqueue_dedupes_persistent_condition():
    clock = FakeTime(1_000_000)
    notifier = SelfHealingNotifier(now_fn=clock)
    svc = AnomalyDetectorService(notifier, detectors={}, now_fn=clock)
    for i in range(5):   # the same condition re-detected every sweep
        svc.enqueue(GoalViolations(AnomalyType.GOAL_VIOLATION, i,
                                   fixable_violated_goals=["RackAwareGoal"]))
    assert len(svc._queue) == 1
    assert svc._queue[0].anomaly.detection_time_ms == 4


def test_raising_detector_does_not_stop_sweep():
    """One broken detector must not stop the sweep: the healthy detectors
    still run and enqueue, and the failure is counted and visible in the
    state snapshot (not just a log line)."""
    clock = FakeTime(1_000_000)
    notifier = SelfHealingNotifier(now_fn=clock)
    calls = {"working": 0}

    def broken():
        raise RuntimeError("injected detector failure")

    def working():
        calls["working"] += 1
        return GoalViolations(AnomalyType.GOAL_VIOLATION, clock(),
                              fixable_violated_goals=["RackAwareGoal"])

    # "broken" iterates first, proving the sweep continues past it
    svc = AnomalyDetectorService(
        notifier, detectors={"broken": broken, "working": working},
        now_fn=clock)
    assert svc.sweep() == 1
    assert calls["working"] == 1
    clock.t += svc.interval_ms + 1
    assert svc.sweep() == 1            # still sweeping on later rounds
    assert calls["working"] == 2
    assert svc.metrics["detector_failures"] == 2
    assert svc.detector_failures == {"broken": 2}
    snap = svc.state_snapshot()
    assert snap["detectorFailures"] == {"broken": 2}
    # the healthy detector's anomalies actually made it into the queue
    kinds = {q.anomaly.anomaly_type for q in svc._queue}
    assert AnomalyType.GOAL_VIOLATION in kinds


def _service_app(overrides=None):
    """Full app with self-healing on; returns (app, adapter)."""
    from cruise_control_tpu.app import CruiseControlApp
    from cruise_control_tpu.common.config import CruiseControlConfig
    from cruise_control_tpu.executor.executor import FakeClusterAdapter
    md = _metadata()
    cfg = CruiseControlConfig({
        "optimizer.engine": "greedy",
        "partition.metrics.window.ms": W,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "execution.progress.check.interval.ms": 1,
        "failed.brokers.file.path": "",
        "self.healing.enabled": True,
        **(overrides or {})})
    adapter = FakeClusterAdapter(
        {f"{p.topic}-{p.partition}": tuple(p.replicas)
         for p in md.partitions}, latency_polls=1)
    app = CruiseControlApp(cfg, StaticMetadataSource(md),
                           SyntheticLoadSampler(seed=7),
                           cluster_adapter=adapter)
    app.load_monitor._now = lambda: 4 * W
    for w in range(4):
        app.load_monitor.sample_once(now_ms=w * W + 30_000)
    return app, adapter


def test_disk_failure_detected_and_fixed_end_to_end():
    """Kill a disk in the fake cluster → DiskFailureDetector (wired through
    the adapter's describe_logdirs) → notifier FIX → fix_offline_replicas
    executes through the executor."""
    app, adapter = _service_app()
    adapter.fail_disk(0, "/data1")
    n = app.anomaly_detector.sweep()
    assert n >= 1
    kinds = {q.anomaly.anomaly_type for q in app.anomaly_detector._queue}
    assert AnomalyType.DISK_FAILURE in kinds
    app.anomaly_detector.handle_pending()
    fixed = [h for h in app.anomaly_detector.history
             if h["anomaly"]["type"] == "DISK_FAILURE"]
    assert fixed and fixed[-1]["action"] == "FIX"
    assert app.anomaly_detector.metrics["fixes_triggered"] >= 1


def test_slow_broker_detected_through_monitor_history():
    """Slow a broker in the monitor's broker-sample stream → SlowBrokerFinder
    (wired on load_monitor.broker_metric_history) detects and escalates."""
    from cruise_control_tpu.monitor.sampler import BrokerMetricSample
    app, adapter = _service_app({"num.partition.metrics.windows": 8,
                                 "slow.broker.demotion.score": 3})
    finder = app.anomaly_detector.detectors["slow_broker"]
    # broker 3's log-flush time escalates while peers stay flat; the finder
    # needs >= 3 completed windows of own history and 3 consecutive slow
    # detections before it reports (score_threshold)
    t0 = 4 * W
    windows = []
    for w in range(8):
        now = t0 + w * W
        app.load_monitor._now = lambda now=now: now + W
        for b in range(4):
            flush = 10.0 if (b != 3 or w < 3) else 10.0 * 4.0 ** (w - 2)
            app.load_monitor._ingest_broker_sample(BrokerMetricSample(
                broker_id=b, time_ms=now + 1000, cpu_util=20.0,
                leader_bytes_in=1000.0,
                extra={"log_flush_time_ms": flush}))
        windows.append(finder())
    found = [a for a in windows if a is not None]
    assert found, "slow broker never detected"
    assert 3 in found[-1].slow_brokers_by_time


def test_metric_anomaly_detected_through_monitor_history():
    from cruise_control_tpu.monitor.sampler import BrokerMetricSample
    app, adapter = _service_app({"num.partition.metrics.windows": 8})
    finder = app.anomaly_detector.detectors["metric_anomaly"]
    t0 = 4 * W
    for w in range(8):
        now = t0 + w * W
        app.load_monitor._now = lambda now=now: now + W
        for b in range(4):
            spike = b == 1 and w == 7
            app.load_monitor._ingest_broker_sample(BrokerMetricSample(
                broker_id=b, time_ms=now + 1000,
                cpu_util=95.0 if spike else 20.0, leader_bytes_in=1000.0))
    found = finder()
    assert any(a.broker_id == 1 and a.metric == "cpu" for a in found)


def test_slow_broker_tail_latency_spike_with_flat_mean_detected():
    """SlowBrokerFinder.java:38-77 scores the 99.9th-percentile log-flush
    gauge, not the mean: a broker whose MEAN flush time stays flat while its
    p99.9 tail spikes must still be demoted. The broker aggregator keeps the
    tail column under a MAX window strategy so the spike survives
    aggregation."""
    from cruise_control_tpu.monitor.sampler import BrokerMetricSample
    app, adapter = _service_app({"num.partition.metrics.windows": 8,
                                 "slow.broker.demotion.score": 3})
    finder = app.anomaly_detector.detectors["slow_broker"]
    t0 = 4 * W
    windows = []
    for w in range(8):
        now = t0 + w * W
        app.load_monitor._now = lambda now=now: now + W
        for b in range(4):
            tail = 40.0 if (b != 3 or w < 3) else 40.0 * 4.0 ** (w - 2)
            app.load_monitor._ingest_broker_sample(BrokerMetricSample(
                broker_id=b, time_ms=now + 1000, cpu_util=20.0,
                leader_bytes_in=1000.0,
                extra={"log_flush_time_ms": 10.0,       # mean flat everywhere
                       "log_flush_time_ms_999th": tail}))
        windows.append(finder())
    found = [a for a in windows if a is not None]
    assert found, "tail-latency-spiking broker never detected"
    assert 3 in found[-1].slow_brokers_by_time

    # the history the finder saw really was the percentile column
    hist = app.load_monitor.broker_metric_history()
    assert hist[3]["flush_time_999"][-1] > 100.0
    assert hist[3]["flush_time"][-1] == pytest.approx(10.0)


def test_slow_broker_kafka_raw_type_extras_flow_to_history():
    """The Kafka reporter path stores extras under the RAW type names
    (process_raw_metrics passes them through); the monitor must pick up
    BROKER_LOG_FLUSH_TIME_MS_{MEAN,999TH} just like the short keys."""
    from cruise_control_tpu.monitor.sampler import BrokerMetricSample
    app, adapter = _service_app()
    for w in range(1, 4):
        app.load_monitor._ingest_broker_sample(BrokerMetricSample(
            broker_id=9, time_ms=w * W + 1000, cpu_util=20.0,
            leader_bytes_in=1000.0,
            extra={"BROKER_LOG_FLUSH_TIME_MS_MEAN": 12.0,
                   "BROKER_LOG_FLUSH_TIME_MS_999TH": 220.0}))
    hist = app.load_monitor.broker_metric_history()
    assert hist[9]["flush_time"][-1] == pytest.approx(12.0)
    assert hist[9]["flush_time_999"][-1] == pytest.approx(220.0)


def test_pluggable_anomaly_class_registry():
    """broker.failures.class etc.: a registered subclass is constructed by
    the detector in place of the built-in payload; unknown names and
    non-subclasses are rejected at resolve time."""
    from cruise_control_tpu.detector.anomalies import (
        ANOMALY_CLASS_REGISTRY, BrokerFailures, GoalViolations,
        resolve_anomaly_class)

    class CustomBrokerFailures(BrokerFailures):
        pass

    ANOMALY_CLASS_REGISTRY["CustomBrokerFailures"] = CustomBrokerFailures
    try:
        cls = resolve_anomaly_class("CustomBrokerFailures", BrokerFailures)
        d = BrokerFailureDetector(StaticMetadataSource(_metadata(dead=(2,))),
                                  now_fn=FakeTime(1000), anomaly_class=cls)
        a = d.detect()
        assert type(a) is CustomBrokerFailures
        assert a.failed_brokers_by_time == {2: 1000}
        with pytest.raises(ValueError):
            resolve_anomaly_class("NoSuchClass", BrokerFailures)
        with pytest.raises(ValueError):
            resolve_anomaly_class("CustomBrokerFailures", GoalViolations)
    finally:
        ANOMALY_CLASS_REGISTRY.pop("CustomBrokerFailures", None)


def test_decision_sink_audits_fired_and_selfheal():
    """The decision sink (the flight recorder's feed, ISSUE 14): a detected
    anomaly emits a 'fired' record at sweep time and a 'self-heal' record
    when the notifier routes it to a fix."""
    clock = FakeTime(1_000_000)
    notifier = SelfHealingNotifier(
        broker_failure_alert_threshold_ms=0, self_healing_threshold_ms=0,
        enabled={t: True for t in AnomalyType}, now_fn=clock)
    ctx = _Ctx()
    failures = BrokerFailures(AnomalyType.BROKER_FAILURE, 0,
                              failed_brokers_by_time={3: 0})
    decisions = []
    svc = AnomalyDetectorService(
        notifier, context=ctx,
        detectors={"broker_failure": lambda: failures},
        now_fn=clock, decision_sink=decisions.append)
    assert svc.sweep() == 1
    assert svc.handle_pending() == 1
    assert [d["decision"] for d in decisions] == ["fired", "self-heal"]
    assert decisions[0]["detector"] == "broker_failure"
    assert decisions[0]["anomaly"]["type"] == "BROKER_FAILURE"
    assert decisions[1]["fixResult"] is True


def test_decision_sink_audits_suppressed_and_deferred():
    """IGNORE verdicts audit as 'suppressed'; an ongoing execution audits
    the deferral itself — the queue is invisible otherwise."""
    clock = FakeTime(1_000_000)
    # self-healing disabled => notifier returns IGNORE
    notifier = SelfHealingNotifier(enabled={t: False for t in AnomalyType},
                                   now_fn=clock)
    decisions = []
    svc = AnomalyDetectorService(notifier, context=_Ctx(), detectors={},
                                 now_fn=clock, decision_sink=decisions.append)
    svc.enqueue(GoalViolations(AnomalyType.GOAL_VIOLATION, 0,
                               fixable_violated_goals=["RackAwareGoal"]))
    svc.handle_pending()
    assert [d["decision"] for d in decisions] == ["suppressed"]

    executing = []
    svc2 = AnomalyDetectorService(
        notifier, context=_Ctx(), has_ongoing_execution=lambda: True,
        detectors={}, now_fn=clock, decision_sink=executing.append)
    svc2.enqueue(GoalViolations(AnomalyType.GOAL_VIOLATION, 0,
                                fixable_violated_goals=["RackAwareGoal"]))
    svc2.handle_pending()
    assert [d["decision"] for d in executing] == ["deferred"]
    assert executing[0]["reason"] == "ongoing-execution"
