#!/usr/bin/env python
"""LinkedIn-scale seed robustness sweep (VERDICT r3 weak #6).

Runs the bench configuration at N seeds in ONE process (the compiled
programs are shape-stable, so every seed after the first runs steady-state)
and prints one JSON line per seed plus a summary row:

    python tools/seed_sweep.py [--seeds 10] [--out docs/seed_sweep.json]

Quality contract being hardened: violations -> 0, balancedness 100, and the
soft-cost channel at 0 across seeds — the "equal-or-better OptimizerResult"
claim (OptimizerResult.java:44-53) as a property, not two data points.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CACHE_DIR = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=10)
    ap.add_argument("--out", default="")
    ap.add_argument("--uphill", type=int, default=0,
                    help="lead_uphill_steps for the repair passes")
    ap.add_argument("--polish", type=int, default=-1,
                    help="override polish cycle count (-1 = default)")
    args = ap.parse_args()

    import jax
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from cruise_control_tpu.analyzer import annealer as AN
    from cruise_control_tpu.analyzer import optimizer as OPT
    from cruise_control_tpu.models import fixtures

    cfg = AN.AnnealConfig(num_chains=16, steps=192, swap_interval=64,
                          tries_move=384, tries_lead=64, tries_swap=192)
    opt_kwargs = {}
    if args.uphill:
        from cruise_control_tpu.analyzer.repair import RepairConfig
        opt_kwargs["repair_config"] = RepairConfig(
            lead_uphill_steps=args.uphill)
    if args.polish >= 0:
        opt_kwargs["polish_cycles"] = args.polish
    rows = []
    for seed in range(args.seeds):
        topo, assign = fixtures.synthetic_cluster(
            num_brokers=2_600, num_replicas=500_000, num_racks=40,
            num_topics=30_000, seed=seed)
        if seed == 0:
            # escape kernels (topic-band swap, fused lead descent) dispatch
            # lazily on the first seed that needs them — warm explicitly so
            # every seed row reflects the warmed-service steady state
            OPT.warm_kernels(topo, assign, anneal_config=cfg,
                             repair_config=opt_kwargs.get("repair_config"))
        t0 = time.time()
        r = OPT.optimize(topo, assign, engine="anneal", anneal_config=cfg,
                         seed=seed, **opt_kwargs)
        hard_after = [s.name for s in r.goal_summaries
                      if s.hard and s.violated_after]
        row = {
            "seed": seed,
            "wall_s": round(time.time() - t0, 3),
            "violations_before": len(r.violated_goals_before),
            "violations_after": len(r.violated_goals_after),
            "hard_violations_after": len(hard_after),
            "violated_after": r.violated_goals_after,
            "balancedness_after": round(r.balancedness_after, 2),
            "soft_cost_after": round(sum(s.cost_after
                                         for s in r.goal_summaries
                                         if not s.hard), 3),
            "movements": r.num_replica_movements,
            "leadership": r.num_leadership_movements,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    walls = [r["wall_s"] for r in rows]
    # seed 0's wall includes compiles/cache-loads on a fresh process;
    # steady-state stats use the remaining seeds — the summary carries BOTH
    # means so readers recomputing from the rows get a matching number
    steady = walls[1:] if len(walls) > 1 else walls
    summary = {
        "summary": True,
        "seeds": args.seeds,
        "steady_seeds": (f"1-{args.seeds - 1} (seed 0 pays process warmup)"
                         if args.seeds > 1 else "0 (single seed)"),
        # min/max cover ALL seeds (a cold seed 0 must not hide a budget
        # breach); only the steady MEAN excludes the warmup seed
        "wall_s_min": min(walls), "wall_s_max": max(walls),
        "wall_s_mean_steady": round(sum(steady) / len(steady), 3),
        "wall_s_mean_all": round(sum(walls) / len(walls), 3),
        "first_seed_wall_s": walls[0],
        "all_violations_zero": all(r["violations_after"] == 0 for r in rows),
        "all_hard_violations_zero": all(r["hard_violations_after"] == 0
                                        for r in rows),
        "all_balancedness_100": all(r["balancedness_after"] == 100.0
                                    for r in rows),
        "max_soft_cost_after": max(r["soft_cost_after"] for r in rows),
        "movements_min": min(r["movements"] for r in rows),
        "movements_max": max(r["movements"] for r in rows),
    }
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"rows": rows, "summary": summary}, f, indent=2)


if __name__ == "__main__":
    main()
