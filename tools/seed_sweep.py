#!/usr/bin/env python
"""LinkedIn-scale seed robustness sweep (VERDICT r3 weak #6).

Runs the bench configuration at N seeds in ONE process (the compiled
programs are shape-stable, so every seed after the first runs steady-state)
and prints one JSON line per seed plus a summary row:

    python tools/seed_sweep.py [--seeds 10] [--out docs/seed_sweep.json]

Quality contract being hardened: violations -> 0, balancedness 100, and the
soft-cost channel at 0 across seeds — the "equal-or-better OptimizerResult"
claim (OptimizerResult.java:44-53) as a property, not two data points.

--warm-curve runs the warm-vs-cold steps-to-quality sweep instead: for each
seed, a deep reference anneal produces the "previous accepted assignment",
then every steps level runs twice — cold (historical random chain inits)
and warm (half the chains seeded from the reference assignment,
annealer.WarmStart) — recording violations and soft cost at equal step
budgets. Results merge into --out under the "warm_vs_cold" key
(fixture-digest- and platform-stamped, docs/PERF.md accounting; the
existing LinkedIn rows are left untouched). Runs at the CPU-feasible
medium scale (300 brokers / 10K replicas) so the curve is reproducible
without an accelerator. Deliberately NOT re-probed here (ROUND5_NOTES
dead ends): add_broker step-binding and basin restarts in healing.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CACHE_DIR = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=10)
    ap.add_argument("--out", default="")
    ap.add_argument("--uphill", type=int, default=0,
                    help="lead_uphill_steps for the repair passes")
    ap.add_argument("--polish", type=int, default=-1,
                    help="override polish cycle count (-1 = default)")
    ap.add_argument("--warm-curve", action="store_true",
                    help="run the warm-vs-cold steps-to-quality sweep "
                         "(merges into --out under 'warm_vs_cold')")
    args = ap.parse_args()

    import jax
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from cruise_control_tpu.analyzer import annealer as AN
    from cruise_control_tpu.analyzer import optimizer as OPT
    from cruise_control_tpu.models import fixtures

    if args.warm_curve:
        return _warm_curve(args, jax, AN, OPT, fixtures)

    cfg = AN.AnnealConfig(num_chains=16, steps=192, swap_interval=64,
                          tries_move=384, tries_lead=64, tries_swap=192)
    opt_kwargs = {}
    if args.uphill:
        from cruise_control_tpu.analyzer.repair import RepairConfig
        opt_kwargs["repair_config"] = RepairConfig(
            lead_uphill_steps=args.uphill)
    if args.polish >= 0:
        opt_kwargs["polish_cycles"] = args.polish
    rows = []
    for seed in range(args.seeds):
        topo, assign = fixtures.synthetic_cluster(
            num_brokers=2_600, num_replicas=500_000, num_racks=40,
            num_topics=30_000, seed=seed)
        if seed == 0:
            # escape kernels (topic-band swap, fused lead descent) dispatch
            # lazily on the first seed that needs them — warm explicitly so
            # every seed row reflects the warmed-service steady state
            OPT.warm_kernels(topo, assign, anneal_config=cfg,
                             repair_config=opt_kwargs.get("repair_config"))
        t0 = time.time()
        r = OPT.optimize(topo, assign, engine="anneal", anneal_config=cfg,
                         seed=seed, **opt_kwargs)
        hard_after = [s.name for s in r.goal_summaries
                      if s.hard and s.violated_after]
        row = {
            "seed": seed,
            "wall_s": round(time.time() - t0, 3),
            "violations_before": len(r.violated_goals_before),
            "violations_after": len(r.violated_goals_after),
            "hard_violations_after": len(hard_after),
            "violated_after": r.violated_goals_after,
            "balancedness_after": round(r.balancedness_after, 2),
            "soft_cost_after": round(sum(s.cost_after
                                         for s in r.goal_summaries
                                         if not s.hard), 3),
            "movements": r.num_replica_movements,
            "leadership": r.num_leadership_movements,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    walls = [r["wall_s"] for r in rows]
    # seed 0's wall includes compiles/cache-loads on a fresh process;
    # steady-state stats use the remaining seeds — the summary carries BOTH
    # means so readers recomputing from the rows get a matching number
    steady = walls[1:] if len(walls) > 1 else walls
    summary = {
        "summary": True,
        "seeds": args.seeds,
        "steady_seeds": (f"1-{args.seeds - 1} (seed 0 pays process warmup)"
                         if args.seeds > 1 else "0 (single seed)"),
        # min/max cover ALL seeds (a cold seed 0 must not hide a budget
        # breach); only the steady MEAN excludes the warmup seed
        "wall_s_min": min(walls), "wall_s_max": max(walls),
        "wall_s_mean_steady": round(sum(steady) / len(steady), 3),
        "wall_s_mean_all": round(sum(walls) / len(walls), 3),
        "first_seed_wall_s": walls[0],
        "all_violations_zero": all(r["violations_after"] == 0 for r in rows),
        "all_hard_violations_zero": all(r["hard_violations_after"] == 0
                                        for r in rows),
        "all_balancedness_100": all(r["balancedness_after"] == 100.0
                                    for r in rows),
        "max_soft_cost_after": max(r["soft_cost_after"] for r in rows),
        "movements_min": min(r["movements"] for r in rows),
        "movements_max": max(r["movements"] for r in rows),
    }
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"rows": rows, "summary": summary}, f, indent=2)


#: step budgets for the warm-vs-cold curve; the reference anneal providing
#: the warm source runs at REF_STEPS
CURVE_STEPS = (32, 64, 128, 256)
REF_STEPS = 512


def _warm_curve(args, jax, AN, OPT, fixtures):
    """Warm-vs-cold steps-to-quality curve at the CPU-feasible medium scale.

    Per seed: a REF_STEPS cold anneal produces the warm source (the
    previous accepted assignment a steady-state service carries), then each
    CURVE_STEPS level runs cold and warm at the SAME seed and step budget
    (polish cycles off, so the curve measures the anneal itself, not the
    repair machinery absorbing the difference). Every (steps, warm) pair is
    a distinct static anneal program; the compiled-program cache makes
    seeds after the first steady-state."""
    import numpy as np

    swap = 16          # uniform across levels so steps=32 still swaps
    base = dict(num_chains=32, tries_move=48, tries_lead=8, tries_swap=24)

    def run(topo, assign, steps, seed, warm_start=None):
        cfg = AN.AnnealConfig(steps=steps, swap_interval=swap, **base)
        t0 = time.time()
        r = OPT.optimize(topo, assign, engine="anneal", anneal_config=cfg,
                         seed=seed, polish_cycles=0, warm_start=warm_start)
        return r, round(time.time() - t0, 3)

    curve = []
    digest = None
    for seed in range(args.seeds):
        topo, assign = fixtures.random_cluster(
            fixtures.ClusterProperties(num_racks=10, num_brokers=300,
                                       num_replicas=10_000, num_topics=500),
            seed=3140 + seed)
        if digest is None:
            digest = fixtures.fixture_digest(topo, assign)
        ref, ref_wall = run(topo, assign, REF_STEPS, seed)
        ws = AN.WarmStart(
            broker_of=np.asarray(
                jax.device_get(ref.final_assignment.broker_of), np.int32),
            leader_of=np.asarray(
                jax.device_get(ref.final_assignment.leader_of), np.int32),
            fraction=0.5)

        def row_of(r, wall):
            return {
                "violations_after": len(r.violated_goals_after),
                "hard_violations_after": sum(
                    1 for s in r.goal_summaries if s.hard and s.violated_after),
                "soft_cost_after": round(sum(
                    s.cost_after for s in r.goal_summaries if not s.hard), 3),
                "balancedness_after": round(r.balancedness_after, 2),
                "wall_s": wall,
            }

        for steps in CURVE_STEPS:
            cold, cw = run(topo, assign, steps, seed)
            warm, ww = run(topo, assign, steps, seed, warm_start=ws)
            row = {"seed": seed, "steps": steps,
                   "cold": row_of(cold, cw), "warm": row_of(warm, ww)}
            curve.append(row)
            print(json.dumps(row), flush=True)
        curve.append({"seed": seed, "steps": REF_STEPS, "reference": True,
                      "cold": row_of(ref, ref_wall)})
        print(json.dumps(curve[-1]), flush=True)

    # per-level aggregation: is warm at this budget no worse than cold?
    levels = {}
    for steps in CURVE_STEPS:
        rs = [c for c in curve if c["steps"] == steps and "warm" in c]
        levels[str(steps)] = {
            "warm_soft_cost_max": max(c["warm"]["soft_cost_after"] for c in rs),
            "cold_soft_cost_max": max(c["cold"]["soft_cost_after"] for c in rs),
            "warm_violations_max": max(c["warm"]["violations_after"]
                                       for c in rs),
            "cold_violations_max": max(c["cold"]["violations_after"]
                                       for c in rs),
            "warm_no_worse_all_seeds": all(
                c["warm"]["violations_after"] <= c["cold"]["violations_after"]
                and c["warm"]["soft_cost_after"]
                <= c["cold"]["soft_cost_after"] + 1e-9 for c in rs),
        }
    out = {
        "fixture": {"kind": "random_cluster", "num_brokers": 300,
                    "num_replicas": 10_000, "num_topics": 500,
                    "seed_base": 3140, "digest": digest},
        "platform": jax.default_backend(),
        "config": dict(base, swap_interval=swap, ref_steps=REF_STEPS,
                       warm_fraction=0.5, polish_cycles=0),
        "curve": curve,
        "levels": levels,
    }
    print(json.dumps({"summary": True, "levels": levels}), flush=True)
    if args.out:
        data = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                data = json.load(f)
        data["warm_vs_cold"] = out
        with open(args.out, "w") as f:
            json.dump(data, f, indent=2)


if __name__ == "__main__":
    main()
