"""Deterministic audit replay: re-run a flight-recorded tick and assert the
proposal reproduces bit-identically.

The flight recorder (obs/flightrec.py) pins every tick record to its inputs
(``inputsDigest`` / the fixture digest) and its outcome (``proposalDigest``,
a sha256 over the final placement + leadership arrays). This tool closes the
loop: given an exported log, it rebuilds the recorded inputs, re-runs the
decision, and compares digests — turning any recorded anomaly into an
offline repro.

Two record sources replay:

- ``scenario:<name>`` records (exported by the simulator) carrying a
  ``scenarioSpec`` context — the scenario is rebuilt from the spec and
  re-run on the virtual clock; the record at the same ``seq`` must
  reproduce **byte-identically** (the whole canonical JSONL line, digests
  included).
- ``fixture:<name>`` records written by this tool's ``record`` mode — the
  named models.fixtures builder is re-invoked, its content digest checked
  against the pin, and ``analyzer.optimizer.optimize`` re-run with the
  recorded settings; the resulting ``proposalDigest`` must match bit-for-bit.

Usage::

    # record one optimizer tick on a fixture (LinkedIn scale: synthetic_cluster)
    python tools/replay_tick.py record --fixture unbalanced --out /tmp/f.jsonl

    # replay any recorded tick from an exported log
    python tools/replay_tick.py replay --log /tmp/f.jsonl
    python tools/replay_tick.py replay --log flight.jsonl --seq 7
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional


class ReplayError(AssertionError):
    """A replayed tick failed to reproduce its record."""


def _pick_record(records, seq: Optional[int]) -> dict:
    ticks = [r for r in records if r.get("kind") == "tick"]
    if not ticks:
        raise ReplayError("log contains no tick records")
    if seq is None:
        return ticks[-1]
    for r in ticks:
        if r.get("seq") == seq:
            return r
    raise ReplayError(f"no tick record with seq={seq} "
                      f"(have {[r['seq'] for r in ticks]})")


# --------------------------------------------------------------- fixture mode

def _optimize_kwargs(args: dict) -> dict:
    """Recorded optimizeArgs → OPT.optimize kwargs (shared by record and
    replay so both sides derive the call the same way)."""
    kwargs = {"seed": args.get("seed", 0),
              "engine": args.get("engine", "auto")}
    if args.get("goals"):
        kwargs["goal_names"] = tuple(args["goals"])
    if args.get("anneal"):
        from cruise_control_tpu.analyzer.annealer import AnnealConfig
        kwargs["anneal_config"] = AnnealConfig(**args["anneal"])
    return kwargs


def record_fixture_tick(fixture: str, seed: int = 0, engine: str = "auto",
                        goals=None, fixture_kwargs=None, anneal=None) -> str:
    """Run one optimizer tick on ``models.fixtures.<fixture>()`` and return
    a single-record canonical flight-recorder JSONL pinning inputs and
    proposal. ``fixture_kwargs`` parameterizes the fixture builder (e.g.
    synthetic_cluster shapes); ``anneal`` is an AnnealConfig field dict."""
    from cruise_control_tpu.analyzer import optimizer as OPT
    from cruise_control_tpu.models import fixtures as FX
    from cruise_control_tpu.obs.flightrec import (FlightRecorder,
                                                  assignment_digest)
    import numpy as np

    fixture_kwargs = dict(fixture_kwargs or {})
    topo, assign = getattr(FX, fixture)(**fixture_kwargs)
    opt_args = {"seed": seed, "engine": engine,
                "goals": list(goals) if goals else None,
                "fixtureKwargs": fixture_kwargs or None,
                "anneal": dict(anneal) if anneal else None}
    res = OPT.optimize(topo, assign, **_optimize_kwargs(
        {**opt_args, "anneal": anneal}))
    rec = FlightRecorder(now_fn=lambda: 0.0)  # pinned clock: canonical bytes
    rec.set_context(source=f"fixture:{fixture}",
                    fixtureDigest=FX.fixture_digest(topo, assign))
    rec.record("tick", {
        "outcome": "computed",
        "engine": res.engine,
        "decodePath": res.decode_path,
        "healPath": res.heal_path,
        "fallbackReason": res.fallback_reason,
        "violatedGoalsBefore": res.violated_goals_before,
        "violatedGoalsAfter": res.violated_goals_after,
        "numReplicaMovements": res.num_replica_movements,
        "numLeadershipMovements": res.num_leadership_movements,
        "proposalDigest": assignment_digest(
            np.asarray(res.final_assignment.broker_of),
            np.asarray(res.final_assignment.leader_of)),
        "optimizeArgs": opt_args,
    })
    return rec.export_jsonl()


def _replay_fixture(record: dict) -> dict:
    from cruise_control_tpu.analyzer import goals as G
    from cruise_control_tpu.analyzer import optimizer as OPT
    from cruise_control_tpu.analyzer import rescore as RS
    from cruise_control_tpu.models import fixtures as FX
    from cruise_control_tpu.obs.flightrec import assignment_digest
    import numpy as np

    name = record["source"].split(":", 1)[1]
    args = record.get("optimizeArgs") or {}
    topo, assign = getattr(FX, name)(**(args.get("fixtureKwargs") or {}))
    got_inputs = FX.fixture_digest(topo, assign)
    if got_inputs != record.get("fixtureDigest"):
        raise ReplayError(
            f"fixture {name!r} no longer matches the recorded inputs: "
            f"digest {got_inputs} != recorded {record.get('fixtureDigest')} "
            "(the generator changed — the recorded tick is not replayable "
            "against it)")
    res = OPT.optimize(topo, assign, **_optimize_kwargs(args))
    got = assignment_digest(np.asarray(res.final_assignment.broker_of),
                            np.asarray(res.final_assignment.leader_of))
    if got != record["proposalDigest"]:
        raise ReplayError(
            f"proposal did NOT reproduce: digest {got} != recorded "
            f"{record['proposalDigest']}")
    # independent verdict audit: re-derive the after-state goal verdicts on
    # rescore's scoring pipeline (thresholds frozen from the INITIAL state,
    # exactly as the optimizer evaluates a proposal) rather than trusting
    # the optimizer's own report, and compare with the recorded list
    goal_names = tuple(args["goals"]) if args.get("goals") else G.DEFAULT_GOALS
    names_ext, violated, _pen = RS.score_state(
        topo, res.final_assignment, goal_names, None, initial_assign=assign)
    audited = [g for g, v in zip(names_ext, violated) if v]
    recorded = record.get("violatedGoalsAfter")
    if recorded is not None and audited != list(recorded):
        raise ReplayError(
            f"verdict audit mismatch: recomputed {audited} != recorded "
            f"{list(recorded)}")
    return {"mode": "fixture", "fixture": name, "seq": record["seq"],
            "inputsDigest": record.get("fixtureDigest"),
            "proposalDigest": got, "violatedGoalsAfter": audited,
            "reproduced": True}


# -------------------------------------------------------------- scenario mode

def _replay_scenario(record: dict) -> dict:
    from cruise_control_tpu.obs.flightrec import canonical_record, load_jsonl
    from cruise_control_tpu.simulator import Scenario, run_scenario

    spec = record.get("scenarioSpec")
    if not spec:
        raise ReplayError(
            f"record from {record.get('source')!r} carries no scenarioSpec "
            "(scenarios with custom workloads/faults embed none) — replay "
            "it by re-running the original scenario code instead")
    sc = Scenario(
        name=spec["name"], seed=spec["seed"], ticks=spec["ticks"],
        tick_ms=spec["tick_ms"], num_brokers=spec["num_brokers"],
        num_racks=spec["num_racks"], topics=tuple(spec["topics"]),
        partitions_per_topic=spec["partitions_per_topic"], rf=spec["rf"],
        warmup_ticks=spec["warmup_ticks"],
        latency_polls=spec.get("latency_polls", 1),
        config_overrides=tuple(
            (k, v) for k, v in spec.get("config_overrides", [])))
    card = run_scenario(sc)
    rerun = {r["seq"]: r for r in load_jsonl(card.flight_log or "")}
    if record["seq"] not in rerun:
        raise ReplayError(
            f"re-run produced no record with seq={record['seq']} "
            f"(have {sorted(rerun)})")
    got, want = canonical_record(rerun[record["seq"]]), canonical_record(record)
    if got != want:
        raise ReplayError(
            "replayed record is NOT byte-identical:\n"
            f"  recorded: {want}\n  replayed: {got}")
    return {"mode": "scenario", "scenario": spec["name"],
            "seq": record["seq"],
            "inputsDigest": record.get("inputsDigest"),
            "proposalDigest": record.get("proposalDigest"),
            "reproduced": True}


def replay_log(text: str, seq: Optional[int] = None) -> dict:
    """Replay one tick record from an exported log; raises ReplayError if it
    does not reproduce bit-identically."""
    from cruise_control_tpu.obs.flightrec import load_jsonl

    record = _pick_record(load_jsonl(text), seq)
    source = str(record.get("source") or "")
    if source.startswith("fixture:"):
        return _replay_fixture(record)
    if source.startswith("scenario:"):
        return _replay_scenario(record)
    raise ReplayError(f"record source {source!r} is not replayable "
                      "(expected fixture:<name> or scenario:<name>)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="record/replay flight-recorded optimizer ticks")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rec = sub.add_parser("record", help="record one fixture tick to JSONL")
    rec.add_argument("--fixture", required=True,
                     help="models.fixtures builder name "
                          "(e.g. unbalanced, synthetic_cluster)")
    rec.add_argument("--seed", type=int, default=0)
    rec.add_argument("--engine", default="auto")
    rec.add_argument("--goals", default=None,
                     help="comma-separated goal list (default goals if unset)")
    rec.add_argument("--fixture-args", default=None,
                     help="JSON kwargs for the fixture builder, e.g. "
                          '\'{"num_brokers": 2600, "num_replicas": 50000}\'')
    rec.add_argument("--anneal", default=None,
                     help="JSON AnnealConfig fields, e.g. "
                          '\'{"num_chains": 8, "steps": 16}\'')
    rec.add_argument("--out", default="-", help="output path (- = stdout)")
    rep = sub.add_parser("replay", help="replay a recorded tick from a log")
    rep.add_argument("--log", required=True,
                     help="flight-recorder JSONL (exported by GET "
                          "/flightrecorder, the simulator scorecard, or "
                          "this tool's record mode)")
    rep.add_argument("--seq", type=int, default=None,
                     help="record to replay (default: the last tick record)")
    args = ap.parse_args(argv)

    if args.cmd == "record":
        goals = ([g for g in args.goals.split(",") if g.strip()]
                 if args.goals else None)
        out = record_fixture_tick(
            args.fixture, seed=args.seed, engine=args.engine, goals=goals,
            fixture_kwargs=json.loads(args.fixture_args)
                           if args.fixture_args else None,
            anneal=json.loads(args.anneal) if args.anneal else None)
        if args.out == "-":
            sys.stdout.write(out)
        else:
            with open(args.out, "w") as f:
                f.write(out)
        return 0

    with open(args.log) as f:
        text = f.read()
    try:
        verdict = replay_log(text, seq=args.seq)
    except ReplayError as e:
        print(f"REPLAY FAILED: {e}", file=sys.stderr)
        return 1
    print(json.dumps(verdict, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
