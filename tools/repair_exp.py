"""Ad-hoc repair/anneal knob experiments on the LinkedIn-scale model.

Usage: python tools/repair_exp.py [--sources N] [--steps N] [--seeds a,b]
Prints one JSON line per seed with wall-clock + quality, mirroring the
bench's steady-state measurement (second run in-process is the one that
matters; the first run pays compile/cache-load).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sources", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=256)
    ap.add_argument("--swap-partners", type=int, default=12)
    ap.add_argument("--claim-rounds", type=int, default=4)
    ap.add_argument("--seeds", default="1,2")
    args = ap.parse_args()

    import jax
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from cruise_control_tpu.analyzer import annealer as AN
    from cruise_control_tpu.analyzer import optimizer as OPT
    from cruise_control_tpu.analyzer import repair as REP
    from cruise_control_tpu.models import fixtures

    topo, assign = fixtures.synthetic_cluster(
        num_brokers=2_600, num_replicas=500_000, num_racks=40,
        num_topics=30_000, seed=0)
    cfg = AN.AnnealConfig(num_chains=16, steps=args.steps, swap_interval=64,
                          tries_move=384, tries_lead=64, tries_swap=192)
    rcfg = REP.RepairConfig(fused_sources=args.sources,
                            swap_partners=args.swap_partners,
                            claim_rounds=args.claim_rounds)

    for i, s in enumerate(int(x) for x in args.seeds.split(",")):
        t0 = time.time()
        r = OPT.optimize(topo, assign, engine="anneal", anneal_config=cfg,
                         seed=s, repair_config=rcfg)
        dt = time.time() - t0
        print(json.dumps({
            "seed": s, "wall_s": round(dt, 2),
            "sources": args.sources, "steps": args.steps,
            "viol_after": len(r.violated_goals_after),
            "hard_after": sum(1 for g in r.goal_summaries
                              if g.hard and g.violated_after),
            "balancedness": round(r.balancedness_after, 2),
            "soft_cost_after": round(sum(g.cost_after for g in r.goal_summaries
                                         if not g.hard), 3),
            "moves": r.num_replica_movements,
            "leads": r.num_leadership_movements,
        }), flush=True)


if __name__ == "__main__":
    main()
