"""graftlint engine: file walking, rule dispatch, baseline bookkeeping.

The analyzer is a findbugs-style gate for JAX/XLA hazards (the reference
project runs findbugs + a config-key audit in CI; this is the JAX-native
equivalent).  Rules are AST passes producing :class:`Finding`s; a checked-in
baseline file suppresses *known* findings (each with a one-line
justification), so only NEW violations fail the gate.

Baseline entries are keyed by a line-number-free fingerprint —
``code|relpath|stripped-source-line`` — with an occurrence count, so edits
elsewhere in a file never churn the baseline.  A finding fails the gate when
its fingerprint's occurrence count exceeds the baselined count.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: repo root (graftlint lives at <root>/tools/graftlint)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str        # rule id, e.g. "G002"
    path: str        # repo-relative posix path
    line: int        # 1-based
    col: int         # 0-based
    message: str
    snippet: str     # stripped source line the finding sits on

    @property
    def fingerprint(self) -> str:
        return f"{self.code}|{self.path}|{self.snippet}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.code} {self.message}")


class ModuleContext:
    """Parsed module handed to every per-file rule."""

    def __init__(self, path: str, source: str, root: str = REPO_ROOT):
        self.abspath = os.path.abspath(path)
        self.path = os.path.relpath(self.abspath, root).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # parent links (ast has none); rules use them for context checks
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self._jit_cache = None

    def snippet_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, code: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(code=code, path=self.path, line=line, col=col,
                       message=message, snippet=self.snippet_at(line))

    @property
    def jit_functions(self):
        """Jitted functions in this module (lazily computed once)."""
        if self._jit_cache is None:
            from tools.graftlint import rules
            self._jit_cache = rules.find_jit_functions(self.tree)
        return self._jit_cache


# --------------------------------------------------------------------------
# Rule registry
# --------------------------------------------------------------------------

#: per-file rules: fn(ModuleContext) -> Iterable[Finding]
FILE_RULES: Dict[str, Tuple[str, callable]] = {}
#: project rules: fn(root, paths) -> Iterable[Finding]; run once per lint
PROJECT_RULES: Dict[str, Tuple[str, callable]] = {}


def file_rule(code: str, name: str):
    def deco(fn):
        FILE_RULES[code] = (name, fn)
        return fn
    return deco


def project_rule(code: str, name: str):
    def deco(fn):
        PROJECT_RULES[code] = (name, fn)
        return fn
    return deco


def _ensure_rules_loaded():
    from tools.graftlint import rules  # noqa: F401  (registers on import)


# --------------------------------------------------------------------------
# Lint drivers
# --------------------------------------------------------------------------

def iter_py_files(paths: Sequence[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, n)
                           for n in sorted(names) if n.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def lint_source(source: str, path: str = "fixture.py",
                select: Optional[Sequence[str]] = None,
                root: str = REPO_ROOT) -> List[Finding]:
    """Lint a source string (unit-test entry point). ``path`` is the
    pretended repo location — rules scoped to hot-path modules key off it."""
    _ensure_rules_loaded()
    ctx = ModuleContext(os.path.join(root, path), source, root=root)
    findings: List[Finding] = []
    for code, (_, fn) in sorted(FILE_RULES.items()):
        if select and code not in select:
            continue
        findings.extend(fn(ctx))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code))


def lint(paths: Sequence[str], select: Optional[Sequence[str]] = None,
         root: str = REPO_ROOT, with_project_rules: bool = True
         ) -> List[Finding]:
    """Lint files/directories; returns ALL findings (baseline not applied)."""
    _ensure_rules_loaded()
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        with open(f, encoding="utf-8") as fh:
            source = fh.read()
        try:
            ctx = ModuleContext(f, source, root=root)
        except SyntaxError as e:
            findings.append(Finding(
                code="G000", path=os.path.relpath(f, root).replace(os.sep, "/"),
                line=e.lineno or 1, col=0,
                message=f"syntax error: {e.msg}", snippet=""))
            continue
        for code, (_, rule_fn) in sorted(FILE_RULES.items()):
            if select and code not in select:
                continue
            findings.extend(rule_fn(ctx))
    if with_project_rules:
        for code, (_, rule_fn) in sorted(PROJECT_RULES.items()):
            if select and code not in select:
                continue
            findings.extend(rule_fn(root, paths))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code))


# --------------------------------------------------------------------------
# Baseline
# --------------------------------------------------------------------------

def load_baseline(path: str = DEFAULT_BASELINE) -> Dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return {e["fingerprint"]: e for e in data.get("suppressions", [])}


def save_baseline(findings: Iterable[Finding], path: str = DEFAULT_BASELINE,
                  old: Optional[Dict[str, dict]] = None) -> None:
    """Write a baseline covering ``findings``, preserving the justifications
    of entries already present in ``old``."""
    old = old if old is not None else load_baseline(path)
    counts: Dict[str, int] = {}
    lines: Dict[str, int] = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
        lines.setdefault(f.fingerprint, f.line)
    entries = []
    for fp in sorted(counts):
        prev = old.get(fp, {})
        entries.append({
            "fingerprint": fp,
            "count": counts[fp],
            # line is informational only (fingerprints are line-free); it
            # points a reader at one current occurrence
            "line": lines[fp],
            "justification": prev.get("justification",
                                      "TODO: justify or fix"),
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "suppressions": entries}, fh, indent=1)
        fh.write("\n")


def prune_stale_baseline(findings: Sequence[Finding],
                         path: str = DEFAULT_BASELINE,
                         codes: Optional[set] = None) -> Tuple[int, List[str]]:
    """Drop baseline entries whose fingerprint matches no current finding.

    Unlike ``save_baseline`` (which rewrites counts from the current
    findings), live entries are preserved verbatim — count, line, and
    justification untouched — so pruning is a pure deletion and never
    widens a suppression.  When ``codes`` is given (a ``--rules``-filtered
    run), only entries for those rule codes are eligible — a filtered run
    must not drop entries its rules never produced.  Returns
    ``(kept, dropped_fingerprints)``.
    """
    old = load_baseline(path)
    live = {f.fingerprint for f in findings}
    dropped = [fp for fp in old if fp not in live
               and (codes is None or fp.split("|", 1)[0] in codes)]
    if dropped:
        # keep everything NOT dropped — a filtered run's out-of-scope
        # entries are neither live nor dropped and must survive the rewrite
        dropped_set = set(dropped)
        entries = [old[fp] for fp in sorted(old) if fp not in dropped_set]
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"version": 1, "suppressions": entries}, fh, indent=1)
            fh.write("\n")
    return len(old) - len(dropped), dropped


#: path prefixes the baseline may NOT suppress: findings here always fail
#: the gate (the greenfield observability package starts — and must stay —
#: hazard-free; inline ``# graftlint: disable=Gnnn`` markers still work,
#: since those carry their justification in the source under review)
BASELINE_FREE_PATHS = ("cruise_control_tpu/obs/",)


def apply_baseline(findings: Sequence[Finding],
                   baseline: Dict[str, dict]
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Split findings into (new, suppressed) and report stale fingerprints.

    Per fingerprint, the first ``count`` occurrences are suppressed; any
    beyond that are new.  Baseline entries matching nothing are stale —
    reported so the baseline can shrink as hazards get fixed, but stale
    entries do not fail the gate (they'd make every fix a two-step dance).
    Findings under :data:`BASELINE_FREE_PATHS` are never suppressed.
    """
    seen: Dict[str, int] = {}
    new: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        seen[f.fingerprint] = seen.get(f.fingerprint, 0) + 1
        if any(f.path.startswith(p) for p in BASELINE_FREE_PATHS):
            new.append(f)
            continue
        allowed = baseline.get(f.fingerprint, {}).get("count", 0)
        (suppressed if seen[f.fingerprint] <= allowed else new).append(f)
    stale = [fp for fp in baseline if fp not in seen]
    return new, suppressed, stale


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX/XLA hazard + concurrency static analyzer "
                    "(rules G001-G012, G101-G105)")
    parser.add_argument("paths", nargs="*",
                        default=["cruise_control_tpu", "bench.py"],
                        help="files/directories to lint "
                             "(default: cruise_control_tpu bench.py)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline suppression file")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding (ignore the baseline)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline to cover current findings "
                             "(keeps existing justifications)")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule codes to run (e.g. "
                             "G001,G002)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule codes to run (alias of "
                             "--select; merged when both are given)")
    parser.add_argument("--prune-stale", action="store_true",
                        help="drop baseline entries whose fingerprints no "
                             "longer match any finding, then exit")
    parser.add_argument("--no-project-rules", action="store_true",
                        help="skip whole-project rules (G007/G102); they "
                             "walk the whole package")
    args = parser.parse_args(argv)

    select = None
    if args.select or args.rules:
        select = [c for spec in (args.select, args.rules) if spec
                  for c in spec.split(",") if c]
    os.chdir(REPO_ROOT)
    findings = lint(args.paths, select=select,
                    with_project_rules=not args.no_project_rules)

    if args.write_baseline:
        save_baseline(findings, path=args.baseline)
        print(f"graftlint: wrote {len(findings)} suppression(s) to "
              f"{os.path.relpath(args.baseline, REPO_ROOT)}")
        return 0

    if args.prune_stale:
        kept, dropped = prune_stale_baseline(
            findings, path=args.baseline,
            codes=set(select) if select else None)
        for fp in dropped:
            print(f"graftlint: pruned {fp}")
        print(f"graftlint: baseline: {kept} kept, {len(dropped)} pruned")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, suppressed, stale = apply_baseline(findings, baseline)
    for f in new:
        print(f.format())
    if stale:
        print(f"graftlint: note: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (fixed findings — run "
              f"--write-baseline to prune):", file=sys.stderr)
        for fp in stale:
            print(f"  {fp}", file=sys.stderr)
    print(f"graftlint: {len(new)} new finding(s), "
          f"{len(suppressed)} baselined, {len(stale)} stale")
    return 1 if new else 0
