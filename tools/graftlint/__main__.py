from tools.graftlint.engine import main

raise SystemExit(main())
