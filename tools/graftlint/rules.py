"""graftlint rules G001-G012: JAX/XLA hazard AST passes.

Each rule is registered with the engine and yields :class:`engine.Finding`s.
The rules are deliberately heuristic — a static pass cannot prove an array is
on-device — but every heuristic errs toward catching the hazard class and
relies on the baseline (plus ``# graftlint: disable=Gnnn`` inline markers)
for the handful of deliberate exceptions.

Rule catalog (docs/linting.md has the long-form rationale):

G001  Python ``if``/``while``/``assert`` on a traced value inside a jitted
      function — trace-time branching silently bakes one path per trace or
      raises ConcretizationTypeError.
G002  Implicit host sync inside a hot-path loop — ``.item()``,
      ``float()``/``int()``/``bool()`` coercion or ``np.asarray`` on device
      values; each one is a blocking device round-trip mid-loop.
G003  Device allocation (``jnp.*`` constructors, ``jax.device_put``) inside
      a Python-level loop body — hoistable uploads that serialize dispatch.
G004  Non-static Python state captured by a jitted function — mutable
      default args, reads of mutable module globals, ``global`` statements.
G005  dtype-promotion hazard: host ``np.*`` array constructors without an
      explicit dtype in device-adjacent code (numpy defaults are
      float64/int64; x64-disabled JAX silently downcasts, x64-enabled JAX
      silently upcasts the whole expression).
G006  Retrace storms from statics: ``static_argnums``/``static_argnames``
      on high-cardinality values (every distinct value is a full retrace).
G007  Config keys defined but never consumed by source (the reference's
      config-key audit, as a lint rule).
G008  Forbidden impurity inside a jitted function — ``np.random``/
      ``random``/``time``/``open``/``os.environ``/``print`` execute at
      trace time only and silently freeze into the compiled program.
G009  Silent broad exception swallow — an ``except Exception:`` /
      ``except BaseException:`` / bare ``except:`` block that neither
      logs, re-raises, nor carries a ``# graftlint: disable=G009``
      justification turns a permanently-failing path invisible.
G010  Fresh-wrapper-per-call retrace hazard: ``jax.jit(...)`` or
      ``partial(jax.jit, ...)`` evaluated inside a function body builds a
      new callable (and a new jit cache) on every invocation of the
      enclosing function — zero cache hits, one trace+compile per call.
      The static twin of ``retrace_sentinel()``
      (cruise_control_tpu/common/sentinels.py): the sentinel catches the
      storm at runtime, this rule catches it in review.
G011  Raw wall-clock in control-plane paths: direct ``time.time()`` /
      ``time.sleep()`` calls in ``app.py``, ``executor/``, ``monitor/``
      or ``detector/`` bypass the injected ``now_fn``/``sleep_fn`` clock
      seams, so the virtual-time simulator (and any deterministic replay)
      silently reads the host clock. References like ``clock=time.time``
      in a default argument ARE the seam and are not flagged — only
      calls. Deliberate wall-clock sites carry a baseline entry with a
      justification.
G012  Unbalanced/leaked tracer span: ``tracer.span(...)`` or
      ``start_span(...)`` called anywhere but as a ``with`` context item.
      An unexited span never pops the tracer's thread-local parent stack
      (every later span on the thread mis-parents under it) and never
      records.  ``cruise_control_tpu/obs/`` is gated baseline-free: a
      finding there can only be fixed, never suppressed.

Concurrency family (G101-G105) — lock discipline over the service's daemon
threads and pools, paired with the runtime sanitizer in
``cruise_control_tpu/common/sanitizer.py``:

G101  Unguarded shared-attribute access: for each class owning a
      ``threading.Lock/RLock`` attribute, the set of ``self._x`` attributes
      mutated under ``with self._lock`` is inferred (cross-method: a
      private helper reached only from lock-held call sites counts as
      lock-held), and any access to those attributes outside the lock
      flags.
G102  Lock-order cycle: nested ``with lockA: ... with lockB:`` acquisition
      pairs collected project-wide form a directed graph; an edge on a
      cycle is a lock-order inversion candidate (deadlock).
G103  Background ``threading.Thread`` started without a shutdown path —
      fire-and-forget ``Thread(...).start()`` or a stored thread that no
      method ever ``join()``s.
G104  Check-then-act on guarded state outside the lock: an ``if`` whose
      test reads a guarded attribute (directly or through a same-class
      method/property) and whose body writes one, with the guarding lock
      not held.
G105  Blocking call while a lock is held — ``time.sleep``,
      ``future.result()``, ``Event.wait()``, ``Queue.get(timeout=...)``,
      or an adapter RPC inside a lock-held region serializes every other
      thread behind the slow operation.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, FrozenSet, Iterator, List, NamedTuple, Optional

from tools.graftlint.engine import (
    Finding, ModuleContext, file_rule, project_rule)

# --------------------------------------------------------------------------
# Shared helpers
# --------------------------------------------------------------------------

#: modules whose loops are the measured wall-clock (G002/G005 scope)
HOT_PATH_MODULES = frozenset({
    "cruise_control_tpu/analyzer/annealer.py",
    "cruise_control_tpu/analyzer/repair.py",
    "cruise_control_tpu/analyzer/optimizer.py",
    "cruise_control_tpu/analyzer/greedy.py",
    "cruise_control_tpu/analyzer/objective.py",
    "cruise_control_tpu/analyzer/intra_broker.py",
    "cruise_control_tpu/ops/aggregates.py",
    "cruise_control_tpu/ops/stats.py",
    "cruise_control_tpu/parallel/sharding.py",
})

#: attribute reads of a traced value that are trace-safe (static metadata)
SAFE_TRACED_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "aval",
                               "sharding", "weak_type"})

#: static_argnames/static_argnums entries that are almost certainly
#: high-cardinality (one retrace per distinct value)
SUSPECT_STATIC_NAMES = frozenset({"seed", "key", "rng", "rng_key", "prng_key",
                                  "index", "idx", "step", "offset", "start",
                                  "stop", "value", "threshold"})

_NP_ROOTS = frozenset({"np", "numpy"})
_JNP_ROOTS = frozenset({"jnp"})


class JitInfo(NamedTuple):
    node: ast.AST            # FunctionDef / AsyncFunctionDef / Lambda
    static_names: FrozenSet[str]


def _attr_root(node: ast.AST) -> Optional[str]:
    """Root Name of a dotted chain: ``jax.numpy.zeros`` -> ``jax``."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_jit_ref(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` / ``<anything>.jit`` reference."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    return isinstance(node, ast.Attribute) and node.attr == "jit"


def _is_partial_ref(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id in ("partial", "_partial")
    return isinstance(node, ast.Attribute) and node.attr == "partial"


def _jit_call_statics(call: ast.Call, fn: Optional[ast.AST] = None
                      ) -> FrozenSet[str]:
    """static argument NAMES of a jit()/partial(jit, ...) call; positional
    static_argnums resolve through ``fn``'s signature when given."""
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names.extend(_str_elems(kw.value))
        elif kw.arg == "static_argnums" and fn is not None:
            params = _param_names(fn)
            for i in _int_elems(kw.value):
                if 0 <= i < len(params):
                    names.append(params[i])
    return frozenset(names)


def _str_elems(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _int_elems(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def find_jit_functions(tree: ast.Module) -> List[JitInfo]:
    """Functions that run under ``jax.jit``: decorated directly, decorated
    via ``partial(jax.jit, ...)``, or wrapped by a module-level
    ``name = jax.jit(fn, ...)`` assignment."""
    by_name: Dict[str, ast.AST] = {}
    out: List[JitInfo] = []
    seen = set()

    def add(fn, statics):
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(JitInfo(fn, statics))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            for dec in node.decorator_list:
                if _is_jit_ref(dec):
                    add(node, frozenset())
                elif isinstance(dec, ast.Call):
                    if _is_jit_ref(dec.func):           # @jax.jit(...)
                        add(node, _jit_call_statics(dec, node))
                    elif (_is_partial_ref(dec.func) and dec.args
                          and _is_jit_ref(dec.args[0])):  # @partial(jax.jit,)
                        add(node, _jit_call_statics(dec, node))
    # module-level  f_jit = jax.jit(f, static_argnames=...)
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call)
                and _is_jit_ref(stmt.value.func) and stmt.value.args
                and isinstance(stmt.value.args[0], ast.Name)):
            fn = by_name.get(stmt.value.args[0].id)
            if fn is not None:
                add(fn, _jit_call_statics(stmt.value, fn))
    return out


def _enclosing_function(ctx: ModuleContext, node: ast.AST) -> Optional[ast.AST]:
    """Innermost function whose BODY executes ``node``.  Decorators and
    default-argument expressions run in the surrounding scope at def time,
    so a def entered via its decorator_list/signature does not count."""
    prev: ast.AST = node
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            via_signature = (prev in cur.decorator_list
                             or isinstance(prev, ast.arguments)
                             or prev is cur.returns)
            if not via_signature:
                return cur
        elif isinstance(cur, ast.Lambda):
            if not isinstance(prev, ast.arguments):
                return cur
        prev, cur = cur, ctx.parents.get(cur)
    return None


def _jit_scope_nodes(ctx: ModuleContext) -> Dict[int, JitInfo]:
    """Map id(function node) -> JitInfo for every jitted function."""
    return {id(ji.node): ji for ji in ctx.jit_functions}


def _in_jit_scope(ctx: ModuleContext, node: ast.AST) -> Optional[JitInfo]:
    """Innermost-to-outermost: is ``node`` inside a jitted function?  A
    nested def inside a jitted function traces with it, so ancestors count."""
    jit_nodes = _jit_scope_nodes(ctx)
    cur = ctx.parents.get(node)
    while cur is not None:
        if id(cur) in jit_nodes:
            return jit_nodes[id(cur)]
        cur = ctx.parents.get(cur)
    return None


def _suppressed(ctx: ModuleContext, node: ast.AST, code: str) -> bool:
    """Inline escape hatch: ``# graftlint: disable=G003`` on the line."""
    line = ctx.snippet_at(getattr(node, "lineno", 0))
    marker = "graftlint: disable"
    if marker not in line:
        return False
    tail = line.split(marker, 1)[1]
    return "=" not in tail or code in tail


def _loop_body_nodes(fn_or_mod: ast.AST) -> Iterator[ast.AST]:
    """Nodes lexically inside a For/While BODY, with function boundaries
    resetting the loop context (a def inside a loop defines code, it does
    not run it per iteration)."""
    emitted = set()

    def walk(node, in_loop):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                walk(child, False)
                continue
            if isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                # iter/test run per-iteration too — count them as in-loop
                walk(child, True)
                continue
            if in_loop and id(child) not in emitted:
                emitted.add(id(child))
                yield_nodes.append(child)
            walk(child, in_loop)

    yield_nodes: List[ast.AST] = []
    walk(fn_or_mod, False)
    return iter(yield_nodes)


def _mentions_root(node: ast.AST, roots: FrozenSet[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in roots:
            return True
    return False


def _contains_device_get(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("device_get", "block_until_ready")):
            return True
    return False


def _device_tainted(node: ast.AST) -> bool:
    """Heuristic: the expression touches device values and does not go
    through an explicit jax.device_get."""
    return ((_mentions_root(node, _JNP_ROOTS)
             or any(isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "device_put"
                    for n in ast.walk(node)))
            and not _contains_device_get(node))


def _assignments_in(fn: ast.AST) -> Dict[str, List[ast.AST]]:
    """name -> value expressions assigned to it anywhere in the function."""
    out: Dict[str, List[ast.AST]] = {}
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            for tgt in n.targets:
                for name_node in ast.walk(tgt):
                    if isinstance(name_node, ast.Name):
                        out.setdefault(name_node.id, []).append(n.value)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)) and n.value:
            if isinstance(n.target, ast.Name):
                out.setdefault(n.target.id, []).append(n.value)
    return out


_HOST_BUILTINS = frozenset({"list", "tuple", "dict", "set", "sorted", "range",
                            "len", "enumerate", "min", "max", "sum", "int",
                            "float", "str"})


def _is_host_expr(v: ast.AST) -> bool:
    return (isinstance(v, (ast.List, ast.Tuple, ast.Dict, ast.Set,
                           ast.ListComp, ast.Constant))
            or _contains_device_get(v)
            or (_mentions_root(v, _NP_ROOTS)
                and not _mentions_root(v, _JNP_ROOTS))
            or (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                and v.func.id in _HOST_BUILTINS))


def _host_assigned_name(ctx: ModuleContext, node: ast.AST) -> bool:
    """Bare name whose every assignment in the enclosing function is
    clearly host-side (list/np/device_get expressions)."""
    if not isinstance(node, ast.Name):
        return False
    fn = _enclosing_function(ctx, node)
    if fn is None:
        return False
    vals = _assignments_in(fn).get(node.id)
    return bool(vals) and all(_is_host_expr(v) for v in vals)


# --------------------------------------------------------------------------
# G001 — traced-value Python control flow inside jit
# --------------------------------------------------------------------------

@file_rule("G001", "traced-branch")
def check_traced_branch(ctx: ModuleContext) -> Iterator[Finding]:
    for ji in ctx.jit_functions:
        if isinstance(ji.node, ast.Lambda):
            continue
        traced = (frozenset(_param_names(ji.node))
                  | frozenset(p.arg for p in ji.node.args.kwonlyargs)
                  ) - ji.static_names
        for node in ast.walk(ji.node):
            if not isinstance(node, (ast.If, ast.While, ast.Assert)):
                continue
            test = node.test
            if _is_static_shape_test(test, traced):
                continue
            if _references_traced(test, traced, ctx) \
                    or _calls_jnp(test):
                if _suppressed(ctx, node, "G001"):
                    continue
                kind = type(node).__name__.lower()
                yield ctx.finding(
                    "G001", node,
                    f"Python `{kind}` on a traced value inside a jitted "
                    f"function — branch is baked at trace time (or raises "
                    f"ConcretizationTypeError); use lax.cond/jnp.where")


def _is_static_shape_test(test: ast.AST, traced: FrozenSet[str]) -> bool:
    """``x is None`` / ``x.shape == ...`` style tests are trace-safe."""
    if isinstance(test, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
        return True
    return False


def _references_traced(test: ast.AST, traced: FrozenSet[str],
                       ctx: ModuleContext) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in traced:
            par = ctx.parents.get(n)
            if (isinstance(par, ast.Attribute) and par.value is n
                    and par.attr in SAFE_TRACED_ATTRS):
                continue
            # len(x) on a traced array is static (shape-derived)
            if (isinstance(par, ast.Call) and isinstance(par.func, ast.Name)
                    and par.func.id in ("len", "isinstance")):
                continue
            return True
    return False


def _calls_jnp(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if (isinstance(n, ast.Call)
                and _attr_root(n.func) in _JNP_ROOTS):
            return True
    return False


# --------------------------------------------------------------------------
# G002 — implicit host sync in hot-path loops
# --------------------------------------------------------------------------

@file_rule("G002", "host-sync-in-loop")
def check_host_sync(ctx: ModuleContext) -> Iterator[Finding]:
    if ctx.path not in HOT_PATH_MODULES:
        return
    assigns_by_fn: Dict[int, Dict[str, List[ast.AST]]] = {}

    def name_tainted(node: ast.AST) -> bool:
        root = node
        while isinstance(root, (ast.Attribute, ast.Subscript)):
            root = root.value
        if not isinstance(root, ast.Name):
            return False
        fn = _enclosing_function(ctx, node)
        if fn is None:
            return False
        if id(fn) not in assigns_by_fn:
            assigns_by_fn[id(fn)] = _assignments_in(fn)
        return any(_device_tainted(v)
                   for v in assigns_by_fn[id(fn)].get(root.id, ()))

    for node in _loop_body_nodes(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _suppressed(ctx, node, "G002"):
            continue
        # .item()
        if (isinstance(node.func, ast.Attribute) and node.func.attr == "item"
                and not node.args):
            yield ctx.finding(
                "G002", node,
                "`.item()` inside a hot-path loop — blocking device->host "
                "sync per iteration; batch with jax.device_get outside "
                "the loop")
            continue
        # float()/int()/bool() coercion of device values
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and len(node.args) == 1):
            arg = node.args[0]
            if isinstance(arg, ast.Constant):
                continue
            if _device_tainted(arg) or name_tainted(arg):
                yield ctx.finding(
                    "G002", node,
                    f"`{node.func.id}()` on a device value inside a "
                    f"hot-path loop — implicit sync; hoist one "
                    f"jax.device_get out of the loop")
            continue
        # np.asarray / np.array in a hot loop: on a device value this is an
        # implicit device->host transfer.  Static analysis can't prove
        # residency, so in HOT loops the burden flips: anything not
        # explicitly host-side (device_get'd, np-rooted, or a literal)
        # is flagged — write the transfer explicitly or it blocks the loop.
        if (isinstance(node.func, ast.Attribute)
                and _attr_root(node.func) in _NP_ROOTS
                and node.func.attr in ("asarray", "array") and node.args):
            arg = node.args[0]
            explicitly_host = (
                _contains_device_get(arg)
                or isinstance(arg, (ast.Constant, ast.List, ast.Tuple,
                                    ast.ListComp))
                or _mentions_root(arg, _NP_ROOTS)
                or _host_assigned_name(ctx, arg))
            if not explicitly_host:
                yield ctx.finding(
                    "G002", node,
                    "`np.asarray` on a possibly-device value inside a "
                    "hot-path loop — implicit device->host transfer; "
                    "route it through jax.device_get explicitly (and "
                    "batch it outside the loop)")


# --------------------------------------------------------------------------
# G003 — device allocation / upload inside a Python loop
# --------------------------------------------------------------------------

_JNP_ALLOCS = frozenset({
    "zeros", "ones", "full", "empty", "arange", "asarray", "array", "eye",
    "linspace", "geomspace", "zeros_like", "ones_like", "full_like"})


@file_rule("G003", "alloc-in-loop")
def check_alloc_in_loop(ctx: ModuleContext) -> Iterator[Finding]:
    for node in _loop_body_nodes(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_alloc = (isinstance(func, ast.Attribute)
                    and ((_attr_root(func) in _JNP_ROOTS
                          and func.attr in _JNP_ALLOCS)
                         or func.attr == "device_put"))
        if not is_alloc or _suppressed(ctx, node, "G003"):
            continue
        what = (func.attr if func.attr == "device_put"
                else f"jnp.{func.attr}")
        yield ctx.finding(
            "G003", node,
            f"`{what}` inside a Python loop body — a device "
            f"allocation/upload per iteration; hoist it (or fold the loop "
            f"into the jitted computation)")


# --------------------------------------------------------------------------
# G004 — non-static Python state captured by a jitted function
# --------------------------------------------------------------------------

@file_rule("G004", "nonstatic-capture")
def check_nonstatic_capture(ctx: ModuleContext) -> Iterator[Finding]:
    # module-level names bound to mutable displays
    mutable_globals = set()
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign):
            if isinstance(stmt.value, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(stmt.value, ast.Call)
                    and isinstance(stmt.value.func, ast.Name)
                    and stmt.value.func.id in ("list", "dict", "set")):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        mutable_globals.add(tgt.id)
    for ji in ctx.jit_functions:
        fn = ji.node
        if isinstance(fn, ast.Lambda):
            continue
        # (a) mutable default arguments
        for default in fn.args.defaults + [d for d in fn.args.kw_defaults
                                           if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                if not _suppressed(ctx, default, "G004"):
                    yield ctx.finding(
                        "G004", default,
                        f"mutable default argument on jitted `{fn.name}` — "
                        f"captured state is baked at first trace and never "
                        f"re-read")
        local = set(_param_names(fn)) | {p.arg for p in fn.args.kwonlyargs}
        local |= set(_assignments_in(fn))
        for n in ast.walk(fn):
            # (b) reads of mutable module globals
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id in mutable_globals and n.id not in local
                    and not _suppressed(ctx, n, "G004")):
                yield ctx.finding(
                    "G004", n,
                    f"jitted `{fn.name}` reads mutable module global "
                    f"`{n.id}` — its value at trace time is frozen into "
                    f"the compiled program; pass it as an argument")
            # (c) global statements
            if isinstance(n, ast.Global) and not _suppressed(ctx, n, "G004"):
                yield ctx.finding(
                    "G004", n,
                    f"`global` inside jitted `{fn.name}` — writes happen "
                    f"at trace time only, not per call")


# --------------------------------------------------------------------------
# G005 — dtype-promotion hazards (dtype-less host numpy allocations)
# --------------------------------------------------------------------------

#: np constructor -> positional index of its dtype parameter
_NP_DTYPE_SLOT = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "array": 1,
                  "asarray": 1, "arange": 3}


@file_rule("G005", "dtype-promotion")
def check_dtype_promotion(ctx: ModuleContext) -> Iterator[Finding]:
    hot = ctx.path in HOT_PATH_MODULES
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and _attr_root(node.func) in _NP_ROOTS
                and node.func.attr in _NP_DTYPE_SLOT):
            continue
        if not (hot or _in_jit_scope(ctx, node)):
            continue
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        if len(node.args) > _NP_DTYPE_SLOT[node.func.attr]:
            continue
        # array/asarray of an existing array is dtype-PRESERVING — the
        # promotion hazard is only dtype INFERENCE from Python literals
        # (lists/tuples/scalar arithmetic -> float64/int64)
        if (node.func.attr in ("array", "asarray") and node.args
                and not _infers_dtype_from_literals(node.args[0])):
            continue
        # wrapped in an explicitly-dtyped converter right above? then the
        # inner constructor's default dtype never escapes
        if _dtype_converted_ancestor(ctx, node):
            continue
        if _suppressed(ctx, node, "G005"):
            continue
        yield ctx.finding(
            "G005", node,
            f"`np.{node.func.attr}` without an explicit dtype in "
            f"device-adjacent code — numpy defaults to float64/int64 and "
            f"the promotion (or silent x64 downcast) follows the array "
            f"into jnp arithmetic; pass dtype= explicitly")


def _infers_dtype_from_literals(arg: ast.AST) -> bool:
    """True when numpy has to GUESS the dtype from Python values: container
    displays, comprehensions, numeric constants, or arithmetic over them.
    Bare names / calls / attributes are assumed to already carry a dtype."""
    if isinstance(arg, (ast.List, ast.Tuple, ast.Set, ast.ListComp,
                        ast.GeneratorExp)):
        return True
    if isinstance(arg, ast.Constant):
        return isinstance(arg.value, (int, float, bool, complex))
    if isinstance(arg, ast.BinOp):  # array*2 keeps the array dtype
        return (_infers_dtype_from_literals(arg.left)
                and _infers_dtype_from_literals(arg.right))
    if isinstance(arg, ast.UnaryOp):
        return _infers_dtype_from_literals(arg.operand)
    if isinstance(arg, ast.IfExp):  # either literal branch can leak
        return (_infers_dtype_from_literals(arg.body)
                or _infers_dtype_from_literals(arg.orelse))
    return False


def _dtype_converted_ancestor(ctx: ModuleContext, node: ast.AST) -> bool:
    cur = ctx.parents.get(node)
    hops = 0
    while cur is not None and hops < 3:
        if isinstance(cur, ast.Call):
            func = cur.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in ("asarray", "array", "astype",
                                      "device_put")):
                has_dtype = (any(kw.arg == "dtype" for kw in cur.keywords)
                             or (func.attr in ("asarray", "array", "astype")
                                 and len(cur.args) >= 2)
                             or func.attr == "astype" and cur.args)
                if has_dtype:
                    return True
            return False
        if not isinstance(cur, (ast.IfExp, ast.BoolOp)):
            return False
        cur = ctx.parents.get(cur)
        hops += 1
    return False


# --------------------------------------------------------------------------
# G006 — retrace storms
# --------------------------------------------------------------------------

@file_rule("G006", "retrace-storm")
def check_retrace_storm(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        is_jit_call = _is_jit_ref(node.func)
        is_partial_jit = (_is_partial_ref(node.func) and node.args
                          and _is_jit_ref(node.args[0]))
        if not (is_jit_call or is_partial_jit):
            continue
        if _suppressed(ctx, node, "G006"):
            continue
        # high-cardinality statics (in-body wrapper creation is G010)
        statics = _jit_call_statics(node)
        suspects = sorted(statics & SUSPECT_STATIC_NAMES)
        if suspects:
            yield ctx.finding(
                "G006", node,
                f"static_argnames includes {suspects} — each distinct "
                f"value is a separate trace+compile (retrace storm); pass "
                f"it as a traced argument or hash a coarser key")


# --------------------------------------------------------------------------
# G007 — config keys defined but never consumed (project rule)
# --------------------------------------------------------------------------

@project_rule("G007", "unwired-config-key")
def check_unwired_config_keys(root: str, paths) -> Iterator[Finding]:
    """The reference's config-key audit as a lint rule: every key the
    ConfigDef defines must be consumed by source code or documented as
    having no effect.  Reuses the mechanical audit behind
    docs/configuration.md (tools/gen_docs.py)."""
    config_rel = "cruise_control_tpu/common/config.py"
    if not os.path.exists(os.path.join(root, config_rel)):
        return
    # the audited package must be importable from the repo root
    for p in (root, os.path.join(root, "tools")):
        if p not in sys.path:
            sys.path.insert(0, p)
    try:
        import gen_docs
        from cruise_control_tpu.common.config import _service_config_def
    except Exception as e:  # package not importable in this env
        yield Finding("G007", config_rel, 1, 0,
                      f"config-key audit could not run: {e}", snippet="")
        return
    consumers = gen_docs._key_consumers()
    config_def = _service_config_def()
    with open(os.path.join(root, config_rel), encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    for name, key in sorted(config_def.keys.items()):
        src, _tests, _via = consumers.get(name, ((), (), None))
        if src or "no effect" in (key.doc or "").lower():
            continue
        line = next((i + 1 for i, text in enumerate(lines)
                     if f'"{name}"' in text), 1)
        yield Finding(
            "G007", config_rel, line, 0,
            f"config key `{name}` is defined but never consumed by source "
            f"— wire it or document it as having no effect",
            snippet=name)


# --------------------------------------------------------------------------
# G008 — forbidden impurity inside jit
# --------------------------------------------------------------------------

def _impurity(node: ast.Call) -> Optional[str]:
    func = node.func
    if isinstance(func, ast.Name):
        if func.id in ("open", "input"):
            return f"`{func.id}()`"
        if func.id == "print":
            return "`print()` (runs at trace time only; use jax.debug.print)"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    root = _attr_root(func)
    dotted = []
    cur = func
    while isinstance(cur, ast.Attribute):
        dotted.append(cur.attr)
        cur = cur.value
    dotted = ".".join(reversed(dotted))
    if root in _NP_ROOTS and "random" in dotted.split("."):
        return f"`np.{dotted}` (host RNG; use jax.random with a threaded key)"
    if root == "random":
        return f"`random.{dotted}` (host RNG; use jax.random)"
    if root == "time" and func.attr in ("time", "perf_counter", "monotonic",
                                        "time_ns"):
        return f"`time.{func.attr}()`"
    if root == "os" and func.attr in ("getenv", "system", "popen"):
        return f"`os.{func.attr}()`"
    return None


# --------------------------------------------------------------------------
# G009 — silent broad exception swallows
# --------------------------------------------------------------------------

#: call attrs that count as "the error was surfaced"
_LOG_METHODS = frozenset({"debug", "info", "warning", "warn", "error",
                          "exception", "critical", "log", "print_exc"})
#: names anywhere in the dotted chain that mark the call as a logging call
_LOGGERISH = frozenset({"logger", "logging", "log", "_logger", "_log",
                        "warnings", "traceback"})


def _broad_handler_label(handler: ast.ExceptHandler) -> Optional[str]:
    """"Exception"/"BaseException"/"bare except" when the handler catches
    (at least) every Exception; None for narrower handlers."""
    t = handler.type

    def name_of(n: ast.AST) -> Optional[str]:
        if isinstance(n, ast.Name) and n.id in ("Exception", "BaseException"):
            return n.id
        if (isinstance(n, ast.Attribute)
                and n.attr in ("Exception", "BaseException")):
            return n.attr
        return None

    if t is None:
        return "except:"
    if isinstance(t, ast.Tuple):
        for e in t.elts:
            nm = name_of(e)
            if nm:
                return f"except {nm}:"
        return None
    nm = name_of(t)
    return f"except {nm}:" if nm else None


def _handler_surfaces(handler: ast.ExceptHandler) -> bool:
    """Does the handler body re-raise or log (logger.*/logging.*/
    warnings.warn/traceback.print_exc)?"""
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr not in _LOG_METHODS:
                continue
            parts = set()
            cur: ast.AST = n.func
            while isinstance(cur, ast.Attribute):
                parts.add(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name):
                parts.add(cur.id)
            if parts & _LOGGERISH:
                return True
    return False


@file_rule("G009", "silent-broad-except")
def check_silent_broad_except(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        label = _broad_handler_label(node)
        if label is None:
            continue
        if _handler_surfaces(node) or _suppressed(ctx, node, "G009"):
            continue
        yield ctx.finding(
            "G009", node,
            f"broad `{label}` swallows the error without logging or "
            f"re-raising — a permanently-failing path becomes invisible; "
            f"log it, re-raise, or justify with `# graftlint: disable=G009`")


# --------------------------------------------------------------------------
# G010 — jit wrapper created inside a function body
# --------------------------------------------------------------------------

@file_rule("G010", "jit-wrapper-in-body")
def check_jit_wrapper_in_body(ctx: ModuleContext) -> Iterator[Finding]:
    """``jax.jit(...)`` / ``partial(jax.jit, ...)`` evaluated inside a
    function body: every invocation of the enclosing function builds a
    fresh callable with an empty jit cache, so the wrapped computation
    trace+compiles on every call.  The static twin of the runtime
    ``retrace_sentinel()`` — hoist the wrapper to module level (the warm
    path's whole shape-bucketing scheme exists so module-level wrappers
    stay hit across ticks)."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            is_partial_jit = (_is_partial_ref(node.func) and node.args
                              and _is_jit_ref(node.args[0]))
            if not (_is_jit_ref(node.func) or is_partial_jit):
                continue
            what = ("`partial(jax.jit, ...)`" if is_partial_jit
                    else "`jax.jit`")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # bare `@jax.jit` on a NESTED def: same hazard, no Call node
            # (the `@jax.jit(...)` / `@partial(jax.jit, ...)` decorator
            # forms are Calls and hit the branch above)
            if not any(_is_jit_ref(d) for d in node.decorator_list
                       if not isinstance(d, ast.Call)):
                continue
            what = "`@jax.jit`"
        else:
            continue
        if _enclosing_function(ctx, node) is None:
            continue
        if _suppressed(ctx, node, "G010"):
            continue
        yield ctx.finding(
            "G010", node,
            f"{what} wrapper created inside a function body — a fresh "
            f"callable per call never hits the jit cache (one full "
            f"trace+compile per invocation); hoist to module level")


# ---------------------------------------------------------------------------
# G011 — raw wall-clock call in a control-plane path
# ---------------------------------------------------------------------------

#: paths whose time flow must route through the injected now_fn/sleep_fn
#: seams (the virtual-time simulator drives exactly these modules)
_G011_PATHS = ("cruise_control_tpu/executor/", "cruise_control_tpu/monitor/",
               "cruise_control_tpu/detector/",
               "cruise_control_tpu/replication/")
_G011_FILES = ("cruise_control_tpu/app.py",)


@file_rule("G011", "raw-wall-clock")
def check_raw_wall_clock(ctx: ModuleContext) -> Iterator[Finding]:
    """Direct ``time.time()`` / ``time.sleep()`` CALLS in the control-plane
    modules the virtual-time simulator drives (app, executor, monitor,
    detector).  Those paths take injected ``now_fn``/``sleep_fn`` seams; a
    raw call reads the host clock even under a ``VirtualClock``, breaking
    deterministic scenario replay.  References (``clock=time.time`` as a
    default argument) are how the seam is *plumbed* and are not flagged;
    the handful of deliberate wall-clock sites live in the baseline with
    justifications."""
    if not (ctx.path in _G011_FILES
            or any(ctx.path.startswith(p) for p in _G011_PATHS)):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in ("time", "sleep")
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"):
            continue
        if _suppressed(ctx, node, "G011"):
            continue
        yield ctx.finding(
            "G011", node,
            f"raw `time.{fn.attr}()` in a control-plane path — route "
            f"through the injected now_fn/sleep_fn clock seam so virtual-"
            f"time simulation and deterministic replay stay exact")


# ---------------------------------------------------------------------------
# G012 — unbalanced / leaked tracer span
# ---------------------------------------------------------------------------

@file_rule("G012", "leaked-span")
def check_leaked_span(ctx: ModuleContext) -> Iterator[Finding]:
    """``tracer.span(...)`` / ``start_span(...)`` used anywhere except as a
    ``with`` context item.  The tracer's thread-local parent stack is
    balanced by ``__exit__``; a span opened without the context manager is
    never popped, so every subsequent span on that thread silently parents
    under it and the buffer leaks an open entry (it also never records, so
    the stage timer misses the sample).  The obs/ package itself is
    additionally gated baseline-free — a finding there can only be fixed,
    never suppressed."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name not in ("span", "start_span"):
            continue
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.withitem) and parent.context_expr is node:
            continue
        if _suppressed(ctx, node, "G012"):
            continue
        yield ctx.finding(
            "G012", node,
            "span opened outside a `with` statement — an unexited span "
            "never pops the thread-local parent stack (all later spans "
            "mis-parent under it) and never records; use "
            "`with tracer.span(...) as sp:`")


@file_rule("G008", "impure-jit")
def check_impure_jit(ctx: ModuleContext) -> Iterator[Finding]:
    for ji in ctx.jit_functions:
        for node in ast.walk(ji.node):
            is_environ = (isinstance(node, ast.Attribute)
                          and node.attr == "environ"
                          and _attr_root(node) == "os")
            what = _impurity(node) if isinstance(node, ast.Call) else None
            if is_environ:
                what = "`os.environ`"
            if what is None or _suppressed(ctx, node, "G008"):
                continue
            yield ctx.finding(
                "G008", node,
                f"{what} inside a jitted function — executes at trace time "
                f"only and its result is frozen into the compiled program")


# ==========================================================================
# Concurrency family G101-G105 — lock-discipline inference
# ==========================================================================

_LOCK_CTOR_NAMES = frozenset({"Lock", "RLock"})

#: method names that mutate their receiver in place (list/dict/set/deque)
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "discard", "pop",
    "popleft", "popitem", "clear", "update", "setdefault", "add", "sort",
    "reverse", "move_to_end"})

#: free functions whose first argument is mutated in place
_MUTATOR_FUNCS = frozenset({"heappush", "heappop", "heapify", "heapreplace",
                            "heappushpop"})


def _is_lock_ctor(node: ast.AST) -> bool:
    """``threading.Lock()`` / ``threading.RLock()`` (or bare ``Lock()``)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in _LOCK_CTOR_NAMES
    return (isinstance(f, ast.Attribute) and f.attr in _LOCK_CTOR_NAMES
            and _attr_root(f) == "threading")


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _module_lock_names(tree: ast.Module) -> FrozenSet[str]:
    out = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
            out.update(t.id for t in stmt.targets if isinstance(t, ast.Name))
    return frozenset(out)


def _map_lexical_held(fn: ast.AST, recognize, out: Dict[int, FrozenSet[str]]
                      ) -> None:
    """For every node in ``fn``'s body, record the set of lock names held
    lexically (via enclosing ``with`` statements).  Nested function bodies
    reset the held set — they run when *called*, not where they're defined."""

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        out[id(node)] = held
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for child in ast.iter_child_nodes(node):
                visit(child, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                # each item's context expr evaluates with the previously
                # listed locks already held — `with a, b:` acquires b
                # under a, exactly like nested withs
                visit(item, inner)
                name = recognize(item.context_expr)
                if name:
                    inner = inner | frozenset((name,))
            for stmt in node.body:
                visit(stmt, inner)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in getattr(fn, "body", []):
        visit(stmt, frozenset())


def _mutated_self_attr(node: ast.AST) -> Optional[str]:
    """Attr name when ``node`` mutates a ``self.<attr>`` value in place or
    rebinds it: direct store/del, subscript store (``self.x[k] = v``,
    ``self.x[k] += v``), mutating method call (``self.x.append(v)``), or a
    heapq-style free function (``heappush(self.x, v)``)."""
    if isinstance(node, ast.Attribute) and isinstance(
            node.ctx, (ast.Store, ast.Del)):
        return _self_attr(node)
    if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)):
        return _self_attr(node.value)
    if isinstance(node, ast.Call):
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _MUTATOR_METHODS):
            return _self_attr(f.value)
        fname = (f.id if isinstance(f, ast.Name)
                 else f.attr if isinstance(f, ast.Attribute) else None)
        if fname in _MUTATOR_FUNCS and node.args:
            return _self_attr(node.args[0])
    return None


class _ClassLockInfo:
    """Per-class lock-discipline model shared by G101/G104/G105.

    ``held_at(node, method)`` is the *effective* held set: lexical ``with``
    nesting plus cross-method inference — a private method (leading
    underscore, not dunder) whose every same-class call site holds lock L
    is analyzed as if its body held L (fixpoint over the private-call
    graph, so helpers of helpers resolve too)."""

    def __init__(self, cls: ast.ClassDef,
                 module_locks: FrozenSet[str] = frozenset()):
        self.cls = cls
        self.methods: List[ast.AST] = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        lock_attrs = set()
        for n in ast.walk(cls):
            if isinstance(n, ast.Assign) and _is_lock_ctor(n.value):
                for t in n.targets:
                    a = _self_attr(t)
                    if a:
                        lock_attrs.add(a)
        self.lock_attrs: FrozenSet[str] = frozenset(lock_attrs)

        def recognize(expr: ast.AST) -> Optional[str]:
            a = _self_attr(expr)
            if a is not None and a in self.lock_attrs:
                return a
            if isinstance(expr, ast.Name) and expr.id in module_locks:
                return expr.id
            return None

        self._lexical: Dict[int, FrozenSet[str]] = {}
        for m in self.methods:
            _map_lexical_held(m, recognize, self._lexical)

        # cross-method propagation: base held set per private method =
        # intersection of the effective held sets at its call sites
        method_names = {m.name for m in self.methods}
        private = {n for n in method_names
                   if n.startswith("_") and not n.startswith("__")}
        # call sites: callee -> [(caller_name, lexical_held_at_site)]
        sites: Dict[str, List] = {}
        for m in self.methods:
            for n in ast.walk(m):
                if isinstance(n, ast.Call):
                    callee = _self_attr(n.func)
                    if callee in private:
                        sites.setdefault(callee, []).append(
                            (m.name, self._lexical.get(id(n), frozenset())))
        self._base: Dict[str, FrozenSet[str]] = {
            n: frozenset() for n in method_names}
        changed = True
        while changed:
            changed = False
            for callee in private:
                callee_sites = sites.get(callee)
                if not callee_sites:
                    continue
                base = None
                for caller, lex in callee_sites:
                    eff = lex | self._base.get(caller, frozenset())
                    base = eff if base is None else (base & eff)
                base = base or frozenset()
                if base != self._base[callee]:
                    self._base[callee] = base
                    changed = True

        # guarded-set inference: attr -> locks it is mutated under (and one
        # witness method name, for the message); __init__ is construction —
        # it happens-before publication and never needs the lock
        self.guards: Dict[str, FrozenSet[str]] = {}
        self.guard_witness: Dict[str, str] = {}
        for m in self.methods:
            if m.name == "__init__":
                continue
            for n in ast.walk(m):
                attr = _mutated_self_attr(n)
                if attr is None or attr in self.lock_attrs:
                    continue
                held = self.held_at(n, m)
                if held:
                    prev = self.guards.get(attr, frozenset())
                    self.guards[attr] = prev | held
                    self.guard_witness.setdefault(attr, m.name)

        # guarded attrs each method READS directly (for G104's
        # property/method indirection in `if self.has_ongoing_execution:`)
        self.method_reads: Dict[str, FrozenSet[str]] = {}
        for m in self.methods:
            reads = {a for n in ast.walk(m)
                     for a in [_self_attr(n)]
                     if a in self.guards and isinstance(n, ast.Attribute)
                     and isinstance(n.ctx, ast.Load)}
            self.method_reads[m.name] = frozenset(reads)

    def held_at(self, node: ast.AST, method: ast.AST) -> FrozenSet[str]:
        return (self._lexical.get(id(node), frozenset())
                | self._base.get(method.name, frozenset()))


def _classes(tree: ast.Module) -> Iterator[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node


# --------------------------------------------------------------------------
# G101 — unguarded access to lock-guarded attributes
# --------------------------------------------------------------------------

@file_rule("G101", "unguarded-shared-attr")
def check_unguarded_shared_attr(ctx: ModuleContext) -> Iterator[Finding]:
    module_locks = _module_lock_names(ctx.tree)
    for cls in _classes(ctx.tree):
        info = _ClassLockInfo(cls, module_locks)
        if not info.lock_attrs or not info.guards:
            continue
        for m in info.methods:
            if m.name == "__init__":
                continue
            for n in ast.walk(m):
                if not (isinstance(n, ast.Attribute)
                        and isinstance(n.ctx, (ast.Load, ast.Store, ast.Del))):
                    continue
                attr = _self_attr(n)
                if attr is None or attr not in info.guards:
                    continue
                if info.held_at(n, m) & info.guards[attr]:
                    continue
                if _suppressed(ctx, n, "G101"):
                    continue
                locks = " / ".join(f"self.{k}"
                                   for k in sorted(info.guards[attr]))
                kind = ("write" if isinstance(n.ctx, (ast.Store, ast.Del))
                        else "read")
                yield ctx.finding(
                    "G101", n,
                    f"`self.{attr}` is written under `{locks}` (e.g. in "
                    f"`{info.guard_witness[attr]}`) but {kind} here without "
                    f"the lock — unguarded shared state across threads")


# --------------------------------------------------------------------------
# G102 — project-wide lock-order cycle detection
# --------------------------------------------------------------------------

@project_rule("G102", "lock-order-cycle")
def check_lock_order_cycles(root: str, paths) -> Iterator[Finding]:
    """Collect every lexically-nested lock acquisition pair ``A held ->
    acquire B`` across the project into a directed graph; any edge on a
    cycle means two code paths acquire the same locks in opposite orders —
    a lock-order inversion (deadlock) candidate.  Lock identity is static:
    ``ClassName.attr`` for ``self.<attr>`` locks, ``module:name`` for
    module-level locks."""
    from tools.graftlint import engine
    abs_paths = [p if os.path.isabs(p) else os.path.join(root, p)
                 for p in paths]
    #: (a, b) -> (relpath, line, snippet) of the first site acquiring b
    #: while a is held
    edges: Dict[tuple, tuple] = {}
    for fpath in engine.iter_py_files(abs_paths):
        try:
            with open(fpath, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            continue
        rel = os.path.relpath(fpath, root).replace(os.sep, "/")
        lines = source.splitlines()
        modname = os.path.splitext(os.path.basename(fpath))[0]
        module_locks = _module_lock_names(tree)

        def scan(fn: ast.AST, recognize) -> None:
            held_map: Dict[int, FrozenSet[str]] = {}
            _map_lexical_held(fn, recognize, held_map)
            for n in ast.walk(fn):
                if not isinstance(n, (ast.With, ast.AsyncWith)):
                    continue
                for item in n.items:
                    b = recognize(item.context_expr)
                    if b is None:
                        continue
                    # the item's own held set includes locks from earlier
                    # items of the same statement (`with a, b:` is an
                    # a -> b acquisition), not just enclosing withs
                    held = held_map.get(id(item), frozenset())
                    for a in held:
                        if a != b and (a, b) not in edges:
                            line = n.lineno
                            snippet = (lines[line - 1].strip()
                                       if line <= len(lines) else "")
                            edges[(a, b)] = (rel, line, snippet)

        def mod_recognize(expr: ast.AST) -> Optional[str]:
            if isinstance(expr, ast.Name) and expr.id in module_locks:
                return f"{modname}:{expr.id}"
            return None

        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                lock_attrs = frozenset(
                    a for n in ast.walk(node)
                    if isinstance(n, ast.Assign) and _is_lock_ctor(n.value)
                    for t in n.targets for a in [_self_attr(t)] if a)

                def cls_recognize(expr, _attrs=lock_attrs, _cls=node.name):
                    a = _self_attr(expr)
                    if a is not None and a in _attrs:
                        return f"{_cls}.{a}"
                    return mod_recognize(expr)

                for m in node.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        scan(m, cls_recognize)
            elif (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and module_locks):
                scan(node, mod_recognize)

    # an edge (a, b) is cyclic iff b reaches a through the graph
    graph: Dict[str, set] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)

    def reaches(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(graph.get(cur, ()))
        return False

    for (a, b) in sorted(edges):
        if not reaches(b, a):
            continue
        rel, line, snippet = edges[(a, b)]
        reverse = next((f"{edges[e][0]}:{edges[e][1]}" for e in sorted(edges)
                        if e != (a, b) and reaches(e[1], a) and e[0] == b),
                       "another path")
        yield Finding(
            "G102", rel, line, 0,
            f"lock-order cycle: `{a}` is held while acquiring `{b}`, but "
            f"the opposite order also occurs (see {reverse}) — lock-order "
            f"inversion (deadlock) candidate; pick one global order",
            snippet=snippet)


# --------------------------------------------------------------------------
# G103 — background thread without a shutdown path
# --------------------------------------------------------------------------

def _is_thread_ctor(func: ast.AST) -> bool:
    if isinstance(func, ast.Name):
        return func.id == "Thread"
    return (isinstance(func, ast.Attribute) and func.attr == "Thread"
            and _attr_root(func) == "threading")


def _enclosing_class(ctx: ModuleContext, node: ast.AST
                     ) -> Optional[ast.ClassDef]:
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = ctx.parents.get(cur)
    return None


def _joins_target(scope: ast.AST, is_target) -> bool:
    """Does ``scope`` contain ``<target>.join(...)``?"""
    for n in ast.walk(scope):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join" and is_target(n.func.value)):
            return True
    return False


@file_rule("G103", "thread-without-shutdown")
def check_thread_shutdown(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node.func)):
            continue
        if _suppressed(ctx, node, "G103"):
            continue
        par = ctx.parents.get(node)
        # Thread(...).start() — nothing retains the thread
        if isinstance(par, ast.Attribute) and par.attr == "start":
            yield ctx.finding(
                "G103", node,
                "fire-and-forget `threading.Thread(...).start()` — no "
                "reference is kept, so nothing can signal shutdown or "
                "`join()` it; store it and pair it with a shutdown "
                "Event + join")
            continue
        if isinstance(par, ast.Assign) and len(par.targets) == 1:
            tgt = par.targets[0]
            attr = _self_attr(tgt)
            if attr is not None:
                cls = _enclosing_class(ctx, node)
                if cls is not None and _joins_target(
                        cls, lambda v, a=attr: _self_attr(v) == a):
                    continue
                yield ctx.finding(
                    "G103", node,
                    f"background thread stored in `self.{attr}` but no "
                    f"method of the class ever calls `self.{attr}.join()` "
                    f"— add a shutdown Event + join path")
                continue
            if isinstance(tgt, ast.Name):
                fn = _enclosing_function(ctx, node) or ctx.tree
                if _joins_target(
                        fn, lambda v, name=tgt.id: isinstance(v, ast.Name)
                        and v.id == name):
                    continue
                yield ctx.finding(
                    "G103", node,
                    f"background thread `{tgt.id}` is never joined in its "
                    f"scope — pair it with a shutdown Event + join (or "
                    f"hand ownership to something that does)")
                continue
        yield ctx.finding(
            "G103", node,
            "`threading.Thread` created without a tracked owner — nothing "
            "can signal shutdown or join it")


# --------------------------------------------------------------------------
# G104 — check-then-act on guarded state outside the lock
# --------------------------------------------------------------------------

@file_rule("G104", "check-then-act")
def check_then_act_outside_lock(ctx: ModuleContext) -> Iterator[Finding]:
    module_locks = _module_lock_names(ctx.tree)
    for cls in _classes(ctx.tree):
        info = _ClassLockInfo(cls, module_locks)
        if not info.lock_attrs or not info.guards:
            continue
        for m in info.methods:
            if m.name == "__init__":
                continue
            for n in ast.walk(m):
                if not isinstance(n, ast.If):
                    continue
                # guarded attrs the test observes — directly, or through a
                # same-class method/property it references
                test_attrs = set()
                for t in ast.walk(n.test):
                    a = _self_attr(t)
                    if a is None:
                        continue
                    if a in info.guards:
                        test_attrs.add(a)
                    elif a in info.method_reads:
                        test_attrs |= info.method_reads[a]
                if not test_attrs:
                    continue
                written = {a for b in n.body for nn in ast.walk(b)
                           for a in [_mutated_self_attr(nn)] if a}
                overlap = test_attrs & written
                if not overlap:
                    continue
                held = info.held_at(n, m)
                racy = sorted(a for a in overlap
                              if not (held & info.guards[a]))
                if not racy or _suppressed(ctx, n, "G104"):
                    continue
                attrs = ", ".join(f"`self.{a}`" for a in racy)
                yield ctx.finding(
                    "G104", n,
                    f"check-then-act on {attrs} outside the guarding lock — "
                    f"the state can change between the test and the act; "
                    f"take the lock around both (double-checked re-test "
                    f"inside is fine)")


# --------------------------------------------------------------------------
# G105 — blocking call while a lock is held
# --------------------------------------------------------------------------

#: receiver-name fragments that mark a `.result()`/`.wait()` receiver as a
#: synchronization object; any domain object may define methods with those
#: names (an HTTP response's .result(), say), so bare-attr matching would
#: drown the rule in false positives
_WAITY_RECEIVER_HINTS = ("future", "fut", "event", "thread", "task",
                         "cond", "promise", "proc", "barrier")


def _blocking_call(node: ast.Call) -> Optional[str]:
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == "sleep" and _attr_root(f) == "time":
        return "`time.sleep`"
    parts = []
    cur: ast.AST = f.value
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    if f.attr in ("result", "wait") and any(
            h in p.lower() for p in parts for h in _WAITY_RECEIVER_HINTS):
        return ("`.result()` on a future" if f.attr == "result"
                else "`.wait()`")
    if f.attr == "get" and any(kw.arg == "timeout" for kw in node.keywords):
        return "`.get(timeout=...)`"
    if any("adapter" in p.lower() for p in parts):
        return f"adapter RPC `.{f.attr}()`"
    return None


@file_rule("G105", "blocking-under-lock")
def check_blocking_under_lock(ctx: ModuleContext) -> Iterator[Finding]:
    module_locks = _module_lock_names(ctx.tree)

    def flag(call: ast.Call, held: FrozenSet[str]) -> Optional[Finding]:
        what = _blocking_call(call)
        if what is None or _suppressed(ctx, call, "G105"):
            return None
        locks = ", ".join(f"`{k}`" for k in sorted(held))
        return ctx.finding(
            "G105", call,
            f"{what} while holding {locks} — every thread contending for "
            f"the lock blocks behind the slow call; move it outside the "
            f"critical section (snapshot under the lock, then call)")

    for cls in _classes(ctx.tree):
        info = _ClassLockInfo(cls, module_locks)
        if not info.lock_attrs and not module_locks:
            continue
        for m in info.methods:
            for n in ast.walk(m):
                if isinstance(n, ast.Call):
                    held = info.held_at(n, m)
                    if held:
                        f = flag(n, held)
                        if f:
                            yield f
    if module_locks:
        def recognize(expr: ast.AST) -> Optional[str]:
            if isinstance(expr, ast.Name) and expr.id in module_locks:
                return expr.id
            return None
        for node in ctx.tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            held_map: Dict[int, FrozenSet[str]] = {}
            _map_lexical_held(node, recognize, held_map)
            for n in ast.walk(node):
                if isinstance(n, ast.Call):
                    held = held_map.get(id(n), frozenset())
                    if held:
                        f = flag(n, held)
                        if f:
                            yield f
