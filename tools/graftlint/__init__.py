"""graftlint — AST-based static analyzer for JAX/XLA hazards (G001-G011)."""

from tools.graftlint.engine import (  # noqa: F401
    Finding, apply_baseline, lint, lint_source, load_baseline, main,
    save_baseline)
